"""RPC: the client proxy object.

Same call surface as the reference client (reference bqueryd/rpc.py:29-207):
attribute access becomes a remote call on a randomly chosen live controller
(``rpc.groupby(...)``, ``rpc.info()``, ...), with ping-verified connection,
reconnect-and-retry, and ``last_call_duration`` timing.

The groupby result path is redesigned: instead of a tar-of-tars that the
client untars and re-aggregates through bcolz (reference bqueryd/rpc.py:135-175),
the controller returns one pickled list of per-shard partial payloads (already
psum-merged across each worker's device mesh) and the client does a value-keyed
NumPy merge + finalize (:mod:`bqueryd_tpu.parallel.hostmerge`).  Mean is a
correct weighted mean; ``legacy_merge=True`` restores the reference's
sum-of-shard-means quirk (reference bqueryd/rpc.py:171) for byte-compatible
comparisons.

An ``RPC`` instance wraps one zmq REQ socket and is therefore
single-thread lockstep, exactly like the reference client: concurrent
callers must each hold their own instance (they are cheap — one ping).
"""

import logging
import os
import pickle
import random
import time

import zmq

import bqueryd_tpu
from bqueryd_tpu import backoff, chaos
from bqueryd_tpu.coordination import coordination_store
from bqueryd_tpu.messages import ErrorMessage, RPCMessage, msg_factory


class RPCError(Exception):
    pass


class RPCBusyError(RPCError):
    """The controller's admission queue rejected the query (backpressure).
    Deliberate and immediate — retry with backoff or shed load upstream."""


class RPC:
    #: capped exponential backoff between retry attempts (timeouts, zmq
    #: errors, BUSY backpressure): base * 2^attempt, capped, stretched by a
    #: deterministic per-socket jitter so a thundering herd of retrying
    #: clients de-synchronizes the same way on every run (shared formula:
    #: bqueryd_tpu.backoff — the controller's failover pacing uses it too)
    BACKOFF_BASE_S = backoff.BACKOFF_BASE_S
    BACKOFF_CAP_S = backoff.BACKOFF_CAP_S

    def __init__(
        self,
        address=None,
        timeout=120,
        coordination_url=None,
        redis_url=None,
        loglevel=logging.INFO,
        retries=3,
        legacy_merge=False,
        client_id=None,
        slo_class=None,
    ):
        bqueryd_tpu.configure_logging(loglevel)
        self.logger = bqueryd_tpu.logger.getChild("rpc")
        chaos.maybe_arm_from_env()
        self.timeout = timeout
        self.retries = retries
        self.legacy_merge = legacy_merge
        # admission quota bucket: sockets sharing a client_id share the
        # controller's per-client quota (BQUERYD_TPU_ADMIT_CLIENT_QUOTA);
        # unset, each socket identity is its own bucket
        self.client_id = client_id
        # SLO class declaration: rides every request envelope (`slo_class`
        # key) so the controller buckets this client's deadline margins and
        # burn rates under the right class (obs.slo; unknown -> "default")
        self.slo_class = slo_class
        self.last_call_duration = None
        #: attempts the most recent call consumed (1 = first try answered;
        #: >1 means timeouts/reconnects/BUSY backoff were absorbed) — the
        #: companion to last_call_duration when diagnosing tail latency
        self.last_call_attempts = None
        #: trace id of the most recent call — feed it to ``rpc.trace(...)``
        #: to pull the controller's per-phase waterfall for that query
        self.last_trace_id = None
        #: per-shard-group phase timings / strategy report of the most
        #: recent groupby reply ({"hints": ..., "effective": ...} for the
        #: latter — what the planner asked for vs what actually compiled)
        self.last_call_timings = None
        self.last_call_strategies = None
        #: per-shard-group merge modes of the most recent groupby reply
        #: ("device" = ICI-mesh collective merge, "host" = hostmerge
        #: fallback, "none" = single payload) — how the answer was merged
        self.last_call_merge_modes = None
        #: answer provenance of the most recent groupby reply (PR 16):
        #: "recompute" | "cached" | "delta" | "rollup" | "subsume" — and,
        #: for subsumption serves, the materialized view that proved it.
        #: None against a pre-PR-16 controller.
        self.last_call_answer_source = None
        self.last_call_subsumed_from = None
        #: client-side deserialize+merge wall of the most recent groupby —
        #: the one segment the controller cannot see; ``autopsy()`` folds it
        #: into the fetched attribution record
        self.last_call_client_merge_s = None
        self._client_merge_by_trace = {}   # trace_id -> seconds (bounded)
        self.identity = os.urandom(8).hex()
        self.store = coordination_store(
            coordination_url or redis_url or bqueryd_tpu.DEFAULT_COORDINATION_URL
        )
        self.context = zmq.Context.instance()
        self.socket = None
        self.address = None
        self.connect(address)

    # -- connection --------------------------------------------------------
    def connect(self, address=None):
        if address:
            candidates = [address]
        else:
            candidates = list(self.store.smembers(bqueryd_tpu.REDIS_SET_KEY))
            random.shuffle(candidates)
        if not candidates:
            raise RPCError("No controllers found in the coordination store")
        for candidate in candidates:
            if self._try_connect(candidate):
                self.address = candidate
                self.logger.debug("connected to controller %s", candidate)
                return
        raise RPCError(f"No controller answered a ping among {candidates}")

    def _try_connect(self, address, ping_timeout=2000):
        self._close_socket()
        self.socket = self.context.socket(zmq.REQ)
        self.socket.identity = self.identity.encode()
        self.socket.setsockopt(zmq.LINGER, 0)
        self.socket.connect(address)
        ping = RPCMessage({"payload": "ping"})
        ping.set_args_kwargs([], {})
        self.socket.send(ping.to_json().encode())
        if self.socket.poll(ping_timeout, zmq.POLLIN):
            reply = msg_factory(self.socket.recv())
            return reply.get("payload") == "pong"
        self._close_socket()
        return False

    def _close_socket(self):
        if self.socket is not None:
            self.socket.close()
            self.socket = None

    # -- proxy -------------------------------------------------------------
    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)

        def remote_call(*args, **kwargs):
            return self._rpc(name, args, kwargs)

        remote_call.__name__ = name
        return remote_call

    def _rpc(self, name, args, kwargs):
        # perf_counter, not time.time(): last_call_duration measures this
        # process's elapsed time, and an NTP step mid-call used to make it
        # negative (the reference's quirk, reference bqueryd/rpc.py:128-129)
        started = time.perf_counter()
        if name == "groupby" and self.legacy_merge:
            # the sum-of-shard-means quirk needs per-shard payloads: disable
            # the controller's batched (pre-merged) shard-group dispatch
            kwargs.setdefault("batch", False)
        # serving-layer kwargs ride the ENVELOPE, not the call params: the
        # controller reads them before any plan compilation, and the worker
        # must never see them as query arguments
        deadline = kwargs.pop("deadline", None)
        priority = kwargs.pop("priority", None)
        msg = RPCMessage({"payload": name})
        if deadline is not None:
            msg.set_deadline(seconds=float(deadline))
        if priority is not None:
            msg["priority"] = priority
        if self.client_id is not None:
            msg["client_id"] = self.client_id
        if self.slo_class is not None:
            msg["slo_class"] = self.slo_class
        # end-to-end tracing: every call mints a root TraceContext; the
        # controller parents its query spans to it and keeps the assembled
        # timeline retrievable via rpc.trace(rpc.last_trace_id)
        from bqueryd_tpu.obs.trace import TraceContext

        ctx = TraceContext.new_root()
        msg.set_trace(ctx)
        self.last_trace_id = ctx.trace_id
        msg.set_args_kwargs(list(args), kwargs)
        wire = msg.to_json().encode()
        last_error = None
        for attempt in range(1, self.retries + 1):
            self.last_call_attempts = attempt
            try:
                if self.socket is None:
                    self.connect()
                # chaos site rpc.call: "timeout" discards the reply window
                # (the retry/backoff path must recover), "disconnect"
                # forces a reconnect storm, "delay" stretches the call
                fault = chaos.fire(
                    "rpc.call", verb=name, attempt=attempt,
                ) if chaos.enabled() else None
                if fault is not None and fault.action == "disconnect":
                    self._close_socket()
                    raise zmq.ZMQError(zmq.ENOTCONN, "chaos: disconnected")
                self.socket.send(wire)
                timed_out = not self.socket.poll(
                    int(self.timeout * 1000), zmq.POLLIN
                )
                if fault is not None and fault.action == "timeout":
                    timed_out = True  # pretend the reply never arrived
                if not timed_out:
                    reply = self.socket.recv()
                    try:
                        result = self._parse_reply(name, reply)
                    except RPCBusyError:
                        # deliberate admission backpressure: retry with
                        # capped exponential backoff inside the attempt
                        # budget (the REQ send/recv cycle completed, so no
                        # reconnect is needed; an identical resend joins
                        # the original run if it got admitted meanwhile)
                        if attempt >= self.retries:
                            raise
                        last_error = "BUSY backpressure"
                        self.logger.info(
                            "rpc %s attempt %d got BUSY, backing off",
                            name, attempt,
                        )
                        time.sleep(self._backoff_delay(attempt))
                        continue
                    self.last_call_duration = time.perf_counter() - started
                    return result
                last_error = f"timeout after {self.timeout}s"
            except zmq.ZMQError as exc:
                last_error = str(exc)
            if attempt >= self.retries:
                # the REQ socket is mid send/recv cycle (send done, reply
                # never read) — drop it so the NEXT call reconnects cleanly
                # instead of hitting EFSM on a poisoned socket
                self._close_socket()
                break
            self.logger.warning(
                "rpc %s attempt %d failed (%s), backing off + reconnecting",
                name, attempt, last_error,
            )
            time.sleep(self._backoff_delay(attempt))
            try:
                self.connect()
            except RPCError as exc:
                last_error = str(exc)
        self.last_call_duration = time.perf_counter() - started
        raise RPCError(
            f"rpc {name} failed after {self.last_call_attempts} attempts: "
            f"{last_error}"
        )

    def _backoff_delay(self, attempt):
        """Capped exponential backoff with deterministic jitter: base *
        2^(attempt-1) up to the cap, stretched by up to 25% keyed on this
        socket's identity + attempt — stable across re-runs (chaos scenarios
        replay bit-for-bit), distinct across clients (no thundering herd)."""
        return backoff.backoff_delay(
            attempt - 1,
            f"{self.identity}:{attempt}",
            base=self.BACKOFF_BASE_S,
            cap=self.BACKOFF_CAP_S,
        )

    def _parse_reply(self, name, reply):
        if name in ("groupby", "query"):
            # both groupby-shaped verbs reply the same pickled result
            # envelope (per-shard payloads + timings); the payload ops are
            # self-describing, so extended operators (topk/quantile)
            # finalize through the same merge path
            return self._parse_groupby_reply(reply)
        msg = msg_factory(reply)
        if isinstance(msg, ErrorMessage):
            raise RPCError(msg.get("payload"))
        if "result" in msg:
            return msg.get_from_binary("result")
        return msg.get("payload")

    def _parse_groupby_reply(self, reply):
        from bqueryd_tpu.models.query import ResultPayload
        from bqueryd_tpu.parallel import hostmerge

        # error replies come back as JSON messages; results as raw pickle
        if reply[:1] == b"{":
            msg = msg_factory(reply)
            raise RPCError(msg.get("payload"))
        envelope = pickle.loads(reply)
        if not envelope.get("ok"):
            if envelope.get("busy"):
                raise RPCBusyError(envelope.get("error"))
            # structured failure envelope (messages.py result schema): the
            # error class + per-attempt worker/fault history replace the
            # blind client timeout the exhaustion path used to produce
            error_class = envelope.get("error_class")
            attempts = envelope.get("attempts") or []
            text = str(envelope.get("error"))
            if error_class:
                trail = "; ".join(
                    f"{a.get('worker')}: {a.get('reason')}"
                    for a in attempts if isinstance(a, dict)
                )
                text = f"{error_class}: {text}"
                if trail:
                    text = f"{text} [attempts: {trail}]"
            err = RPCError(text)
            err.error_class = error_class
            err.attempts = attempts
            raise err
        # client deserialize + merge: the one critical-path segment that
        # happens after the controller sealed the trace — measured here,
        # keyed by trace id, folded into autopsy() records on demand
        merge_clock = time.perf_counter()
        payloads = [ResultPayload.from_bytes(b) for b in envelope["payloads"]]
        self.last_call_timings = envelope.get("timings")
        self.last_call_strategies = envelope.get("strategies")
        self.last_call_merge_modes = envelope.get("merge_modes")
        self.last_call_answer_source = envelope.get("answer_source")
        self.last_call_subsumed_from = envelope.get("subsumed_from")
        if self.legacy_merge:
            result = self._legacy_merge_frames(payloads)
        else:
            merged = hostmerge.merge_payloads(payloads)
            result = hostmerge.payload_to_dataframe(merged)
        self.last_call_client_merge_s = time.perf_counter() - merge_clock
        if self.last_trace_id:
            self._client_merge_by_trace[self.last_trace_id] = (
                self.last_call_client_merge_s
            )
            while len(self._client_merge_by_trace) > 32:
                self._client_merge_by_trace.pop(
                    next(iter(self._client_merge_by_trace))
                )
        return result

    def _legacy_merge_frames(self, payloads):
        """Reference-quirk mode: finalize each shard separately, then re-merge
        every measure with 'sum' — reproducing sum-of-shard-means for mean
        (reference bqueryd/rpc.py:159-173)."""
        import pandas as pd

        from bqueryd_tpu.parallel import hostmerge

        frames = []
        key_cols = None
        for payload in payloads:
            if payload.get("kind") == "empty":
                continue
            key_cols = payload.get("key_cols", key_cols)
            frames.append(
                hostmerge.payload_to_dataframe(hostmerge.merge_payloads([payload]))
            )
        if not frames:
            return pd.DataFrame()
        stacked = pd.concat(frames, ignore_index=True)
        if key_cols is None:
            return stacked
        return stacked.groupby(key_cols, sort=True).sum().reset_index()

    # -- operator-DAG queries ----------------------------------------------
    def query(self, spec, deadline=None, priority=None):
        """The operator-DAG verb: richer shapes than ``groupby`` — broadcast
        hash joins of small dimension tables, per-group top-k, approximate
        quantiles (mergeable sketches), and time-window rollups — compiled
        controller-side into a typed operator DAG
        (:mod:`bqueryd_tpu.plan.dag`; spec shape documented there and in
        the README's "Relational operators" section).  Returns a pandas
        DataFrame like ``groupby``: top-k columns hold per-group
        best-first value arrays, quantile columns hold the sketch
        estimates (error bound <= the op's alpha).  The spec is validated
        client-side first so malformed queries fail without a round trip;
        the controller re-validates authoritatively."""
        from bqueryd_tpu.plan import dag as dagmod

        dagmod.compile_query(spec)
        kwargs = {}
        if deadline is not None:
            kwargs["deadline"] = deadline
        if priority is not None:
            kwargs["priority"] = priority
        return self._rpc("query", (spec,), kwargs)

    # -- streaming ingest --------------------------------------------------
    def append(self, filename, data, deadline=None):
        """Append a dataframe-like batch of rows to a served shard: the
        controller routes the frame to every replica holder of
        ``filename`` (one per distinct (node, data_dir)) and replies once
        ALL holders confirmed.  Returns ``{"filename", "appended",
        "holders": {worker: {...}}}``.  Worker-side, the committed row
        count flips atomically after the chunk data lands, so queries
        racing the append see either the pre- or post-append snapshot —
        never a torn one; repeat queries after the append are served by
        delta maintenance (only the appended chunks re-aggregate).  A
        holder failure raises with the failed workers named — replicas
        may then have diverged; re-issue the append or re-download."""
        kwargs = {}
        if deadline is not None:
            kwargs["deadline"] = deadline
        return self._rpc("append", (filename, data), kwargs)

    # -- query autopsy -----------------------------------------------------
    def autopsy(self, trace_id=None):
        """The attributed critical-path breakdown for one query (default:
        the controller's newest trace): named non-overlapping segments,
        coverage accounting, per-attempt dispatch history.  When this
        client executed the query's merge itself (the usual groupby path),
        the locally measured ``client_deserialize`` segment — invisible to
        the controller, which seals the trace before the client unpickles —
        is folded in and the coverage recomputed over the extended wall."""
        record = self._rpc("autopsy", (trace_id,) if trace_id else (), {})
        if not isinstance(record, dict):
            return record
        merge_s = self._client_merge_by_trace.get(record.get("trace_id"))
        if merge_s:
            segments = record.setdefault("segments", {})
            segments["client_deserialize"] = round(merge_s, 6)
            wall = float(record.get("wall_s") or 0.0) + merge_s
            covered = float(record.get("covered_s") or 0.0) + merge_s
            record["wall_s"] = round(wall, 6)
            record["covered_s"] = round(covered, 6)
            if wall > 0:
                record["coverage"] = round(covered / wall, 4)
        return record

    # -- fleet capacity ----------------------------------------------------
    def capacity(self):
        """The controller's fleet capacity model (``obs.capacity``): per
        worker μ (service rate), λ (dispatch rate), ρ and saturation state
        (ok/warm/saturated/overloaded, hysteresis applied); fleet
        utilization, the predicted saturation knee / headroom QPS, the
        M/G/1-predicted vs measured queue delay and their drift; the
        per-shard dispatch heat map; and the shadow advisor's current
        ``scale_up``/``scale_down``/``rebalance`` recommendations with
        their evidence.  Advisory only — the controller never acts on
        them.  (An explicit method rather than the ``__getattr__`` proxy
        purely for discoverability; the verb is plain ``capacity``.)"""
        return self._rpc("capacity", (), {})

    # -- download helpers (client-local, straight to the store) ------------
    def get_download_data(self):
        """Raw ticket hashes keyed by their full store key — the reference's
        exact shape (reference bqueryd/rpc.py:181-188), for tooling written
        against it."""
        data = {}
        for key in self.store.keys(bqueryd_tpu.REDIS_TICKET_KEY_PREFIX + "*"):
            data[key] = self.store.hgetall(key)
        return data

    def downloads(self):
        """Summaries of in-flight download tickets as ``(ticket,
        "done/total")`` tuples — the reference's output shape (reference
        bqueryd/rpc.py:190-199).  Per-slot detail: ``download_progress()``."""
        out = []
        prefix = bqueryd_tpu.REDIS_TICKET_KEY_PREFIX
        for key, entries in self.get_download_data().items():
            done = sum(1 for v in entries.values() if v.endswith("_DONE"))
            out.append((key[len(prefix):], f"{done}/{len(entries)}"))
        return out

    def download_progress(self):
        """Per-slot download states: ``[(ticket, {(node, fileurl): state})]``
        — richer than the reference's done/total summary; ERROR states are
        visible here."""
        out = []
        prefix = bqueryd_tpu.REDIS_TICKET_KEY_PREFIX
        for key, entries in self.get_download_data().items():
            progress = {}
            for slot, value in entries.items():
                node, _, fileurl = slot.partition("_")
                _, _, state = value.rpartition("_")
                progress[(node, fileurl)] = state
            out.append((key[len(prefix):], progress))
        return out

    def delete_download(self, ticket):
        """Cancel a ticket by deleting its slots; downloaders abort mid-flight
        on the next progress update (reference bqueryd/worker.py:418-428)."""
        key = bqueryd_tpu.REDIS_TICKET_KEY_PREFIX + ticket
        existed = bool(self.store.hgetall(key))
        self.store.delete(key)
        return existed
