"""Measured-cost kernel-strategy calibration: the planner feedback loop.

PR 1's strategy hints are static heuristics: ``choose_strategy`` picks a
route from (rows, estimated groups) against fixed thresholds, and a
``matmul`` hint is purely advisory — ``ops.partial_tables`` re-derives the
same decision, so every hint normalizes to the program the dispatcher would
have picked anyway.  BENCH_DETAIL.json's planner section measured what that
leaves on the table: 0.52 s forced-matmul vs 0.87 s adaptive on the sharded
config — ~40% of wall wherever the MXU route is safe but the heuristic
profitability threshold declines it.

This module closes the loop with MEASURED evidence (the cardinality-adaptive
strategy choice of *Global Hash Tables Strike Back!*, PAPERS.md):

* workers :func:`record` the kernel wall of every un-jit-compile-tainted
  dispatch under its (rows-bucket, groups-bucket, dtype, backend, strategy)
  cell — walls come from the executor's aggregate-phase timer, FLOPs/bytes
  ride along from the PR-3 program registry (``obs.profile`` cost_analysis);
* cells keep an EWMA wall (adapts to drift) plus min/count, optionally
  persisted across restarts (``BQUERYD_TPU_CALIB_PATH``) and gossiped to
  controllers in WRM ``calibration`` summaries (schema in ``messages.py``);
* the controller's :meth:`CalibrationStore.choose` ranks the legal candidate
  strategies: measured cells (>= ``BQUERYD_TPU_CALIB_MIN_SAMPLES``) by their
  EWMA wall, unmeasured ones by an analytical FLOPs/bytes-shaped unit count
  scaled by the measured cells' seconds-per-unit — the cold-start prior.
  A bucket with NO measurements always returns the heuristic unchanged
  (cold-start behaviour is bit-identical to the PR-5 planner), and
  ``BQUERYD_TPU_CALIB=0`` restores it everywhere at once.
* exploration is deterministic and bounded: once the bucket has measured
  data, every ~``1/BQUERYD_TPU_CALIB_EPSILON``-th decision samples the
  least-measured legal candidate (advisory hints only — exploration can
  never emit the binding promotion, so a guard can always decline it).

Control-plane module: stdlib only, no JAX — the controller imports it.
Thread safety: one lock guards all mutable state (declared for the
concurrency lint via ``_bqtpu_guarded_``); file I/O happens outside it.
"""

import json
import math
import os
import threading

#: routes calibration may measure/choose between.  "host" walls are recorded
#: too (host-routed queries are real data points) but never chosen — host
#: routing stays latency-threshold-driven (models.query.host_kernel_rows).
MEASURABLE_STRATEGIES = ("matmul", "scatter", "sort", "host")

#: EWMA weight of a new wall sample: heavy enough to track data/backend
#: drift within ~10 samples, light enough that one noisy wall cannot flip a
#: decision by itself
EWMA_ALPHA = 0.25

#: sample-count cap per cell: keeps merged gossip counts bounded and the
#: EWMA responsive (a cell "full" at 1024 still re-learns in ~10 samples)
MAX_CELL_COUNT = 1024

#: cells kept per store / shipped per WRM summary (LRU-by-update eviction)
MAX_CELLS = 512
MAX_WIRE_CELLS = 128


def enabled():
    """Calibration master switch (read per call: live-tunable).
    ``BQUERYD_TPU_CALIB=0`` restores the PR-5 heuristic planner exactly:
    no recording, no gossip, no calibrated decisions, no binding hints."""
    return os.environ.get("BQUERYD_TPU_CALIB", "1") != "0"


def calib_path():
    """Persistence path for the process store, or None (memory only — the
    default: test/CI processes must not leak samples across runs)."""
    path = os.environ.get("BQUERYD_TPU_CALIB_PATH", "")
    return None if path in ("", "-", "0") else path


def epsilon():
    """Exploration rate in [0, 1]; 0 disables exploration."""
    try:
        eps = float(os.environ.get("BQUERYD_TPU_CALIB_EPSILON", "0.05"))
    except ValueError:
        eps = 0.05
    return min(max(eps, 0.0), 1.0)


def min_samples():
    """Measured walls a cell needs before calibration trusts it."""
    try:
        n = int(os.environ.get("BQUERYD_TPU_CALIB_MIN_SAMPLES", "3"))
    except ValueError:
        n = 3
    return max(n, 1)


def rows_bucket(rows):
    """log2 bucket: data drift within ~2x reuses the same measurements."""
    return int(math.log2(max(int(rows), 1))) if rows else 0


def groups_bucket(groups):
    return rows_bucket(groups)


def dtype_tag(dtypes):
    """Compact dtype dimension of a cell key: what actually changes kernel
    economics is float64 (scatters regardless of route) vs float32 (Dekker
    limbs on the MXU) vs integer (byte limbs).  ``dtypes`` is an iterable of
    dtype-likes; empty (rows-count-only queries) tags as ``int``."""
    tags = set()
    for dt in dtypes or ():
        name = str(getattr(dt, "name", dt))
        if name in ("float64", "f64"):
            tags.add("f64")
        elif name.startswith("float") or name.startswith("bfloat"):
            tags.add("f32")
        else:
            tags.add("int")
    for tag in ("f64", "f32", "int"):
        if tag in tags:
            return tag
    return "int"


def cell_key(rows_b, groups_b, dtype, backend, strategy):
    return f"r{int(rows_b)}|g{int(groups_b)}|{dtype}|{backend}|{strategy}"


def parse_key(key):
    """Inverse of :func:`cell_key`; None for malformed (version-skewed)
    keys — one bad gossip entry must never poison the store."""
    if not isinstance(key, str):
        return None
    parts = key.split("|")
    if len(parts) != 5:
        return None
    rb, gb, dtype, backend, strategy = parts
    if not (rb.startswith("r") and gb.startswith("g")):
        return None
    try:
        rows_b, groups_b = int(rb[1:]), int(gb[1:])
    except ValueError:
        return None
    if strategy not in MEASURABLE_STRATEGIES:
        return None
    return rows_b, groups_b, dtype, backend, strategy


def analytic_units(strategy, rows, groups):
    """Backend-free relative cost of a route at (rows, groups) — the same
    quantities HLO ``cost_analysis`` counts, in arbitrary units: the one-hot
    contraction is rows x groups MACs, the blocked scatter is a per-limb
    rows pass plus its ``blocks x groups`` table, the sort is
    ``rows log rows`` comparisons per limb.  Scale (seconds per unit) is
    learned from whatever cells ARE measured, making this the analytical
    cold-start prior for the unmeasured ones."""
    rows = max(int(rows), 1)
    groups = max(int(groups), 1)
    if strategy == "matmul":
        return float(rows) * groups
    if strategy == "sort":
        return float(rows) * max(math.log2(max(rows, 2)), 1.0) * 8.0
    # scatter: 4 16-bit limb passes over rows + the blocked bucket table,
    # whose blocks x groups cells are written AND reduced (memory-bound) —
    # the term that makes extreme cardinality favour the sort, matching the
    # engine's own _MAX_BLOCK_SEGMENTS economics
    blocks = -(-rows // 65536)
    return float(rows) * 8.0 + float(blocks) * groups * 8.0


class CalibrationStore:
    """Thread-safe calibrated cost model over strategy cells (see module
    docstring).  One instance per process on workers (the global
    :func:`store`), one per controller fed by WRM gossip."""

    _bqtpu_guarded_ = {
        "_lock": (
            "_cells", "_peers", "_decisions", "samples_total",
            "absorbed_total", "_records_since_save",
        ),
    }

    #: records between auto-saves when a persistence path is configured
    SAVE_EVERY = 32

    #: gossip sources tracked before the oldest is evicted
    MAX_PEERS = 256

    def __init__(self, path=None):
        self._lock = threading.Lock()
        self._path = path          # None -> BQUERYD_TPU_CALIB_PATH per call
        self._cells = {}           # key -> cell dict (JSON-safe), own samples
        # source id -> {key: cell}: absorbed peer summaries.  Kept PER
        # SOURCE and REPLACED wholesale on each absorb — a worker's WRM
        # summary is its cumulative state, so re-merging it every heartbeat
        # would double-count the same samples until one noisy wall passed
        # the min-samples floor on repetition alone
        self._peers = {}
        self._decisions = {}       # bucket key -> calibrated-decision count
        self.samples_total = 0
        self.absorbed_total = 0
        self._records_since_save = 0

    # -- recording -----------------------------------------------------------
    def record(self, rows, groups, dtype, backend, strategy, wall_s,
               flops=None, bytes_accessed=None):
        """Fold one measured kernel wall into its cell.  Callers are
        expected to skip compile-tainted walls (a jit-cache miss inflates
        the sample by the compile)."""
        if not enabled() or strategy not in MEASURABLE_STRATEGIES:
            return
        try:
            wall_s = float(wall_s)
        except (TypeError, ValueError):
            return
        if not (wall_s > 0.0) or not math.isfinite(wall_s):
            return
        key = cell_key(
            rows_bucket(rows), groups_bucket(groups), dtype, backend,
            strategy,
        )
        save_now = False
        with self._lock:
            cell = self._cells.pop(key, None)
            if cell is None:
                cell = {"n": 0, "ewma_s": wall_s, "min_s": wall_s}
            cell["n"] = min(cell["n"] + 1, MAX_CELL_COUNT)
            cell["ewma_s"] = (
                cell["ewma_s"] * (1.0 - EWMA_ALPHA) + wall_s * EWMA_ALPHA
            )
            cell["min_s"] = min(cell["min_s"], wall_s)
            if flops:
                cell["flops"] = float(flops)
            if bytes_accessed:
                cell["bytes_accessed"] = float(bytes_accessed)
            # re-insert at the back: dict order is the LRU-by-update order
            self._cells[key] = cell
            while len(self._cells) > MAX_CELLS:
                self._cells.pop(next(iter(self._cells)))
            self.samples_total += 1
            self._records_since_save += 1
            if self._records_since_save >= self.SAVE_EVERY:
                self._records_since_save = 0
                save_now = True
        if save_now:
            self.save()  # file I/O outside the lock

    # -- decisions -----------------------------------------------------------
    def _measured_locked(self, rows_b, groups_b, dtype, candidates):
        """{strategy: (n, ewma_s, units)} over trusted cells of the bucket,
        merged n-weighted across backend (homogeneous-fleet assumption: a
        mixed CPU/TPU fleet's cells stay separate per backend but the
        controller cannot know which backend will serve a dispatch) and —
        when ``dtype`` is None, the controller's stats-only view — across
        dtype tags too.  Must be called with the lock held."""
        floor = min_samples()
        merged = {}
        sources = [self._cells.items()]
        sources.extend(peer.items() for peer in self._peers.values())
        for key, cell in (pair for src in sources for pair in src):
            parsed = parse_key(key)
            if parsed is None:
                continue
            rb, gb, dt, _backend, strategy = parsed
            if rb != rows_b or gb != groups_b or strategy not in candidates:
                continue
            if dtype is not None and dt != dtype:
                continue
            n, ewma = cell.get("n", 0), cell.get("ewma_s")
            if not isinstance(ewma, (int, float)) or n <= 0:
                continue
            prev = merged.get(strategy)
            if prev is None:
                merged[strategy] = [n, float(ewma)]
            else:
                total = prev[0] + n
                prev[1] = (prev[1] * prev[0] + float(ewma) * n) / total
                prev[0] = total
        return {
            s: (n, ewma) for s, (n, ewma) in merged.items() if n >= floor
        }

    def choose(self, total_rows, est_groups, dtype, candidates, heuristic):
        """Pick a strategy for one dispatch from measured evidence.

        Returns ``(strategy, reason)`` with reason one of:

        * ``cold``     — no trusted measurement in this bucket: ``heuristic``
          unchanged (the bit-identical cold-start contract);
        * ``explore``  — deterministic epsilon slot: the least-measured
          legal candidate, as an ADVISORY hint;
        * ``agree``    — MEASURED walls rank the heuristic's route best;
        * ``measured`` — MEASURED walls rank another route best;
        * ``prior``    — the winning route has no measurements of its own
          (ranked by the analytic prior alone): advisory-strength evidence,
          so callers must not make such a choice binding.
        """
        candidates = tuple(
            c for c in candidates if c in MEASURABLE_STRATEGIES
        )
        if (
            not enabled()
            or heuristic not in candidates
            or not candidates
            or total_rows is None
            or est_groups is None
        ):
            return heuristic, "cold"
        rows_b = rows_bucket(total_rows)
        groups_b = groups_bucket(est_groups)
        with self._lock:
            measured = self._measured_locked(
                rows_b, groups_b, dtype, candidates
            )
            if not measured:
                # a cold bucket NEVER deviates (and never explores): today's
                # heuristic, bit for bit
                return heuristic, "cold"
            bucket = f"r{rows_b}|g{groups_b}|{dtype}"
            decision_n = self._decisions.get(bucket, 0) + 1
            self._decisions[bucket] = decision_n
            if len(self._decisions) > MAX_CELLS:
                self._decisions.pop(next(iter(self._decisions)))
        eps = epsilon()
        unmeasured = [c for c in candidates if c not in measured]
        if eps > 0.0 and unmeasured:
            period = max(int(round(1.0 / eps)), 2)
            if decision_n % period == 0:
                # deterministic bounded exploration of the least-measured
                # candidate; advisory by construction (the caller only
                # promotes 'measured'/'agree' choices to binding)
                return unmeasured[0], "explore"
        # seconds-per-analytic-unit learned from the measured cells scales
        # the analytical prior for the unmeasured ones (cost_analysis-shaped
        # FLOPs/bytes grounding, see analytic_units)
        scales = [
            ewma / max(analytic_units(s, total_rows, est_groups), 1.0)
            for s, (_n, ewma) in measured.items()
        ]
        scale = sorted(scales)[len(scales) // 2]
        predicted = {}
        for cand in candidates:
            if cand in measured:
                predicted[cand] = measured[cand][1]
            else:
                predicted[cand] = (
                    analytic_units(cand, total_rows, est_groups) * scale
                )
        best = min(predicted, key=lambda s: (predicted[s], s != heuristic))
        backed = best in measured  # real walls, not prior extrapolation
        if best == heuristic:
            return heuristic, "agree" if backed else "prior"
        # hysteresis: an override must beat the heuristic's own prediction
        # by >10%, or run-to-run noise would flip routes (and recompile
        # programs) endlessly
        if predicted[best] > predicted[heuristic] * 0.9:
            return heuristic, (
                "agree" if heuristic in measured else "prior"
            )
        return best, "measured" if backed else "prior"

    # -- gossip / persistence ------------------------------------------------
    def summary(self, max_cells=MAX_WIRE_CELLS):
        """JSON-safe wire summary (newest-updated cells first) for the WRM
        ``calibration`` key and the persistence file."""
        with self._lock:
            keys = list(self._cells)[-max_cells:]
            cells = {k: dict(self._cells[k]) for k in keys}
            return {
                "v": 1,
                "samples_total": self.samples_total,
                "cells": cells,
            }

    @staticmethod
    def _clean_cells(wire):
        """Validated {key: cell} copies from a wire summary.  Malformed
        entries are dropped one by one — gossip from a version-skewed
        worker must never poison local measurements."""
        if not isinstance(wire, dict):
            return {}
        cells = wire.get("cells")
        if not isinstance(cells, dict):
            return {}
        clean = {}
        for key, cell in cells.items():
            if parse_key(key) is None or not isinstance(cell, dict):
                continue
            n, ewma = cell.get("n"), cell.get("ewma_s")
            if (
                not isinstance(n, int)
                or isinstance(n, bool)
                or n <= 0
                or not isinstance(ewma, (int, float))
                or not math.isfinite(float(ewma))
                or float(ewma) <= 0.0
            ):
                continue
            min_s = cell.get("min_s", ewma)
            entry = {
                "n": min(n, MAX_CELL_COUNT),
                "ewma_s": float(ewma),
                "min_s": float(min_s)
                if isinstance(min_s, (int, float)) else float(ewma),
            }
            for extra in ("flops", "bytes_accessed"):
                value = cell.get(extra)
                if isinstance(value, (int, float)):
                    entry[extra] = float(value)
            clean[key] = entry
            if len(clean) >= MAX_WIRE_CELLS:
                break
        return clean

    def absorb(self, wire, source=None):
        """Fold a peer summary into the model; returns absorbed cell count.

        With ``source`` (the gossip path: one summary per worker per WRM),
        the summary REPLACES that source's previous contribution — a WRM
        summary is the worker's cumulative state, so n-weighted re-merging
        on every heartbeat would double-count the same samples until one
        noisy wall cleared the min-samples floor by repetition alone.
        Without ``source`` (persistence load, legacy callers), cells merge
        n-weighted into the store's own, counts capped."""
        clean = self._clean_cells(wire)
        if not clean:
            return 0
        with self._lock:
            if source is not None:
                self._peers.pop(source, None)
                self._peers[source] = clean
                while len(self._peers) > self.MAX_PEERS:
                    self._peers.pop(next(iter(self._peers)))
                self.absorbed_total += len(clean)
                return len(clean)
            for key, cell in clean.items():
                mine = self._cells.pop(key, None)
                if mine is None:
                    mine = cell
                else:
                    total = mine["n"] + cell["n"]
                    mine["ewma_s"] = (
                        mine["ewma_s"] * mine["n"]
                        + cell["ewma_s"] * cell["n"]
                    ) / total
                    mine["n"] = min(total, MAX_CELL_COUNT)
                    mine["min_s"] = min(mine["min_s"], cell["min_s"])
                    for extra in ("flops", "bytes_accessed"):
                        if extra in cell:
                            mine[extra] = cell[extra]
                self._cells[key] = mine
                while len(self._cells) > MAX_CELLS:
                    self._cells.pop(next(iter(self._cells)))
                self.absorbed_total += 1
        return len(clean)

    def save(self, path=None):
        """Atomic JSON dump (tmp + rename); failures are silent — losing a
        calibration file must never fail a query path."""
        path = path or self._path or calib_path()
        if not path:
            return False
        try:
            payload = json.dumps(self.summary(max_cells=MAX_CELLS))
            tmp = f"{path}.tmp.{os.getpid()}"
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
            with open(tmp, "w") as f:
                f.write(payload)
            os.replace(tmp, path)
            return True
        except OSError:
            return False

    def load(self, path=None):
        """Absorb a previously-saved summary; missing/corrupt files load as
        empty (cold start)."""
        path = path or self._path or calib_path()
        if not path:
            return 0
        try:
            with open(path) as f:
                wire = json.load(f)
        except (OSError, ValueError):
            return 0
        return self.absorb(wire)

    def stats(self):
        """Monitoring/bench snapshot.  ``cells`` counts own AND absorbed
        peer cells (the decision surface); ``samples_total`` counts only
        locally-recorded walls."""
        with self._lock:
            return {
                "cells": len(self._cells)
                + sum(len(p) for p in self._peers.values()),
                "sources": len(self._peers),
                "samples_total": self.samples_total,
                "absorbed_total": self.absorbed_total,
            }


# -- process-global store (workers record into it; WRMs gossip it) -----------

_store = None
_store_lock = threading.Lock()


def store():
    """The process-global worker-side store, lazily created and (when
    ``BQUERYD_TPU_CALIB_PATH`` is set) warmed from the persistence file."""
    global _store
    with _store_lock:
        if _store is None:
            _store = CalibrationStore()
            _store.load()
        return _store


def _reset_for_tests():
    """Fresh process-global store (tests must not leak samples into each
    other's planner decisions)."""
    global _store
    with _store_lock:
        _store = CalibrationStore()
        return _store


def record_sample(rows, groups, dtypes, backend, strategy, wall_s,
                  flops=None, bytes_accessed=None):
    """Worker-side convenience over :meth:`CalibrationStore.record`; a
    recording failure must never reach the query path."""
    if not enabled():
        return
    try:
        store().record(
            rows, groups, dtype_tag(dtypes), backend, strategy, wall_s,
            flops=flops, bytes_accessed=bytes_accessed,
        )
    except Exception:
        pass


def summary_for_wire():
    """The WRM ``calibration`` payload, or None (disabled / nothing yet)."""
    if not enabled():
        return None
    s = store()
    if not s.stats()["cells"]:
        return None
    return s.summary()
