"""Per-shard statistics: gathered by calc workers, advertised in their
WorkerRegisterMessage, consumed by the controller's planner.

A shard's stats are metadata-only reads — nothing is decompressed:

* ``rows`` from the table's meta.json;
* per-column ``min``/``max`` from the chunk-writer stats in each column's
  meta (:meth:`ctable.col_stats`), datetime columns in int64 ns;
* per-column key ``card``inality from whichever cheap source exists:
  a dict column's dictionary length, or the on-disk factorize sidecar
  (``factor.npz``) written by a previous query — the ``uniques`` member is
  read without touching the (much larger) codes array.

``stats_can_match`` is the controller-side twin of
:func:`bqueryd_tpu.ops.predicates.shard_can_match`: it decides from
advertised stats alone whether a shard can contain ANY row matching a
filter conjunction, so provably-empty shards are pruned at plan time and
never dispatched.  It only prunes on plain numeric comparisons (the
controller has no pandas for datetime translation and no dictionaries for
dict-code translation); anything else conservatively matches — the worker's
own ``shard_can_match`` remains the second, stronger pruning line.

Control-plane module: no JAX, no pandas.
"""

import os

import numpy as np

#: numbers the controller can compare against min/max stats without any
#: column-kind translation (bool excluded on purpose: bool storage has no
#: stats anyway)
_NUMBER = (int, float)


def _sidecar_cardinality(table, name):
    """len(uniques) from the column's factorize sidecar, or None.  Loads only
    the stamp + uniques members of the npz — never the row-length codes."""
    path = table._col_path(name, "factor.npz")
    stamp = table.factor_stamp(name)
    if stamp is None or not os.path.exists(path):
        return None
    try:
        with np.load(path, allow_pickle=False) as z:
            if not np.array_equal(z["stamp"], stamp):
                return None
            return int(z["uniques"].shape[0])
    except Exception:
        return None


def column_cardinality(table, name):
    """Best-known distinct-value count for a column, or None (unknown)."""
    if table.kind(name) == "dict":
        dictionary = table.dictionary(name)
        return None if dictionary is None else len(dictionary)
    return _sidecar_cardinality(table, name)


def _chunk_prefix_sig(table, name, count):
    """CRC of the identity (offset, csize, crc) of the first ``count``
    committed chunks of a column — the metadata-only fingerprint the
    incremental gather validates against, so a shard REPLACED in place
    (same name, same-or-more chunks, different bytes) can never pass as
    an append and fold stale min/max into fresh advertisements."""
    import zlib

    committed = getattr(table, "committed_chunks", None)
    if committed is None:
        return None
    chunks = committed(name)
    if chunks is None or len(chunks) < count:
        return None
    sig = 0
    for c in chunks[:count]:
        sig = zlib.crc32(
            f"{c.get('offset')}:{c.get('csize')}:{c.get('crc')};".encode(),
            sig,
        )
    return sig


def gather_table_stats(table, prev=None):
    """One shard's advertised stats (JSON-safe dict).

    ``prev`` is the previous snapshot for the same shard (if any): when the
    table only GREW since it was taken (chunk counts monotonic AND the old
    chunks an unchanged prefix, validated per column by the metadata-only
    ``sig`` fingerprint — the streaming-append signature), per-column work
    is incremental: min/max fold the NEW chunks' zone maps into the
    previous bounds and an unchanged column's cardinality probe (the
    factorize-sidecar npz open, the one non-O(1) read here) is skipped.
    Any non-growth change — including an in-place replacement with
    different content — fails the fingerprint and falls back to the full
    gather."""
    prev_cols = (prev or {}).get("cols") if isinstance(prev, dict) else None
    if not isinstance(prev_cols, dict):
        prev_cols = {}
    cols = {}
    for name in table.names:
        kind = table.kind(name)
        entry = {"kind": kind}
        counts = table.chunk_rows(name) if hasattr(table, "chunk_rows") \
            else None
        nchunks = len(counts) if counts is not None else None
        if nchunks is not None:
            entry["chunks"] = nchunks
            entry["sig"] = _chunk_prefix_sig(table, name, nchunks)
        pentry = prev_cols.get(name)
        grown = (
            isinstance(pentry, dict)
            and pentry.get("kind") == kind
            and nchunks is not None
            and isinstance(pentry.get("chunks"), int)
            and nchunks >= pentry["chunks"]
            # the old chunks must be an UNCHANGED prefix of the current
            # index: an in-place replacement with >= chunks is not growth
            and pentry.get("sig") is not None
            and _chunk_prefix_sig(table, name, pentry["chunks"])
            == pentry["sig"]
        )
        if (
            grown
            and "min" in pentry
            and "max" in pentry
            and nchunks > pentry["chunks"]
        ):
            # fold only the appended chunks' zone maps into the previous
            # bounds; a new chunk without a zone map degrades to col_stats
            maps = table.chunk_zone_maps(name)
            new = (
                maps[pentry["chunks"]:] if maps is not None else [None]
            )
            if all(m is not None for m in new):
                entry["min"] = min(
                    [pentry["min"]] + [m[0] for m in new]
                )
                entry["max"] = max(
                    [pentry["max"]] + [m[1] for m in new]
                )
        if "min" not in entry:
            stats = table.col_stats(name)
            if stats is not None:
                entry["min"], entry["max"] = stats
        if kind == "dict":
            # exact and O(1): the persistent dictionary only ever grows
            dictionary = table.dictionary(name)
            if dictionary is not None:
                entry["card"] = len(dictionary)
        elif grown and nchunks == pentry["chunks"] and "card" in pentry:
            # unchanged column: reuse instead of re-opening the sidecar
            entry["card"] = pentry["card"]
        elif grown and nchunks > pentry["chunks"]:
            # appended column: its factorize sidecar is provably stale
            # (the stamp covers the data bytes), so the probe can only
            # miss — skip it; cardinality re-advertises after the next
            # query re-factorizes and stores a fresh sidecar
            pass
        else:
            card = column_cardinality(table, name)
            if card is not None:
                entry["card"] = card
        cols[name] = entry
    return {"rows": int(table.nrows), "cols": cols}


class StatsCollector:
    """Memoized per-shard stats for a worker's data dir.

    Called from both the worker's main loop and its liveness heartbeat
    thread, so gathering must stay cheap: full stats are memoized per shard
    and re-gathered only when the shard's meta identity or its factorize
    sidecars change (a query writing a new sidecar refreshes the advertised
    cardinality on the next heartbeat)."""

    #: min seconds between full stamp sweeps: inside the window collect()
    #: returns the previous snapshot OBJECT without touching the filesystem,
    #: so per-heartbeat cost is O(1) however many shards/columns exist (the
    #: identity also lets the WRM builder skip re-advertising unchanged
    #: stats, see WorkerBase.prepare_wrm)
    MIN_REFRESH_S = 5.0

    def __init__(self, table_opener=None, min_refresh_s=None):
        self._open = table_opener
        self._memo = {}  # shard name -> (stamp, stats dict)
        self.min_refresh_s = (
            self.MIN_REFRESH_S if min_refresh_s is None else min_refresh_s
        )
        self._snapshot = None
        self._snapshot_names = None
        self._snapshot_ts = 0.0

    def invalidate(self):
        """Drop the snapshot window so the NEXT collect re-stamps every
        shard immediately.  Called by the worker's append path: a grown
        shard must advertise fresh stats on the next heartbeat, not after
        ``min_refresh_s`` — stale controller-side min/max would prune
        shards whose appended rows now match.  Per-shard memos are kept:
        the re-stamp detects the one grown shard and refreshes it
        incrementally."""
        self._snapshot = None
        self._snapshot_names = None
        self._snapshot_ts = 0.0

    def _stamp(self, rootdir, table):
        """Identity of everything the stats derive from: the table meta plus
        every column's factor sidecar mtime (present or absent)."""
        from bqueryd_tpu.storage.ctable import rootdir_cache_key

        parts = [rootdir_cache_key(rootdir)]
        for name in table.names:
            try:
                st = os.stat(table._col_path(name, "factor.npz"))
                parts.append((name, st.st_mtime_ns, st.st_size))
            except OSError:
                parts.append((name, None))
        return tuple(parts)

    def collect(self, data_dir, names):
        """{shard name: stats} for every shard that opens cleanly.  Returns
        the SAME dict object until the refresh window elapses or the shard
        list changes — callers may use identity to detect staleness."""
        import time

        now = time.time()
        if (
            self._snapshot is not None
            and now - self._snapshot_ts < self.min_refresh_s
            and self._snapshot_names == tuple(names)
        ):
            return self._snapshot
        out = {}
        for name in names:
            rootdir = os.path.join(data_dir, name)
            try:
                table = (
                    self._open(rootdir)
                    if self._open is not None
                    else _default_open(rootdir)
                )
                stamp = self._stamp(rootdir, table)
                hit = self._memo.get(name)
                if hit is not None and hit[0] == stamp:
                    out[name] = hit[1]
                    continue
                # stale memo: re-gather INCREMENTALLY against the previous
                # snapshot (append-grown shards fold only the new chunks'
                # zone maps and skip unchanged cardinality probes)
                stats = gather_table_stats(
                    table, prev=hit[1] if hit is not None else None
                )
                self._memo[name] = (stamp, stats)
                out[name] = stats
            except Exception:
                continue  # an unreadable shard simply advertises no stats
        for gone in set(self._memo) - set(names):
            self._memo.pop(gone, None)
        # keep the previous snapshot OBJECT when nothing changed, so the
        # WRM builder's identity check keeps suppressing re-advertisement
        if self._snapshot is not None and out == self._snapshot:
            out = self._snapshot
        self._snapshot = out
        self._snapshot_names = tuple(names)
        self._snapshot_ts = now
        return out


def _default_open(rootdir):
    from bqueryd_tpu.storage.ctable import ctable

    return ctable(rootdir, mode="r", auto_cache=True)


def zone_can_match(lo, hi, op, value):
    """Per-chunk twin of :func:`stats_can_match`: True unless NO value in
    the chunk's ``[lo, hi]`` zone map can satisfy ``(op, value)``.  Values
    are PHYSICAL (the worker translates datetimes to int64 ns before
    calling); anything incomparable conservatively matches — garbage must
    read as "cannot prune", never raise mid-query.

    Only the provable ops prune.  ``!=``/``not in`` are deliberately
    excluded even when ``lo == hi``: a float chunk's zone map skips NaNs,
    and NaN rows *do* satisfy ``!=`` — pruning on bounds alone would drop
    them."""
    try:
        if op == "==":
            return not (value < lo or value > hi)
        if op == ">":
            return hi > value
        if op == ">=":
            return hi >= value
        if op == "<":
            return lo < value
        if op == "<=":
            return lo <= value
        if op == "in":
            if isinstance(value, (list, tuple, set, frozenset)) and value:
                return any(not (v < lo or v > hi) for v in value)
            return True
    except TypeError:
        return True
    return True


def stats_can_match(stats, where_terms):
    """False only if NO row of the shard can satisfy the conjunction, judged
    from advertised stats alone.  Mirrors ``ops.predicates.shard_can_match``
    restricted to plain numeric comparisons; unknown columns, kinds, ops or
    value types conservatively match."""
    cols = stats.get("cols") if isinstance(stats, dict) else None
    if not isinstance(cols, dict):
        cols = {}
    for term in where_terms or []:
        try:
            column, op, value = term
        except (TypeError, ValueError):
            continue
        entry = cols.get(column)
        if not isinstance(entry, dict) or entry.get("kind") != "numeric":
            continue
        lo, hi = entry.get("min"), entry.get("max")
        # advertised bounds must themselves be numbers: garbage stats must
        # read as "cannot prune", never raise mid-launch
        if not isinstance(lo, _NUMBER) or not isinstance(hi, _NUMBER):
            continue
        if op == "in":
            if (
                isinstance(value, (list, tuple, set, frozenset))
                and value
                and all(isinstance(v, _NUMBER) for v in value)
                and all(v < lo or v > hi for v in value)
            ):
                return False
            continue
        if not isinstance(value, _NUMBER) or isinstance(value, bool):
            continue
        if op == "==" and (value < lo or value > hi):
            return False
        if op == ">" and hi <= value:
            return False
        if op == ">=" and hi < value:
            return False
        if op == "<" and lo >= value:
            return False
        if op == "<=" and lo > value:
            return False
    return True
