"""Typed operator DAG: the relational surface beyond filter->groupby->agg.

The engine answered exactly one shape of question — the hardwired
mask -> fold -> aggregate sequence behind ``rpc.groupby`` — while ROADMAP
item 3 calls for compiling logical plans into a small tiled operator DAG
(the *Xorbits* move: automatic operator tiling for distributed data
science; combined with *Taurus NDP*'s push-relational-operators-near-the-
data).  This module is the typed form of that DAG:

* **node types** — :class:`Scan` (fact shards + pushed-down predicates),
  :class:`Filter` (post-join/post-window terms on derived columns),
  :class:`HashJoinBroadcast` (a small dimension table shipped in the
  dispatch envelope; the probe is a gather after factorizing the join
  key), :class:`WindowRollup` (a datetime-bucket derived group key),
  :class:`GroupAgg` (the existing mergeable kernels, unchanged),
  :class:`TopK` (per-group top-k via the sort route: partial = per-shard
  top-k, merge = k-way re-select) and :class:`QuantileSketch` (a
  fixed-bucket DDSketch-style mergeable histogram, so the cross-worker
  merge is bucket-count addition — exactly like the PR-2 metric
  histograms).
* **compile** — :func:`compile_query` turns the ``rpc.query`` spec dict
  into a validated :class:`OperatorDAG`; :func:`dag_from_query` compiles a
  plain :class:`~bqueryd_tpu.models.query.GroupByQuery` (the groupby RPC)
  into the same DAG form, and :meth:`OperatorDAG.plain_groupby_query`
  round-trips it back EXACTLY — plain groupbys compile through the DAG
  path and execute bit-identically to the pre-DAG engine (proven by the
  fuzz corpus).
* **dispatch form** — :func:`groupby_equivalent` derives the
  groupby-shaped ``(plan, kwargs)`` the controller's existing admission /
  pruning / failover / autopsy machinery runs on, so every new operator
  inherits those subsystems for free; the DAG itself rides each
  CalcMessage under the ``dag`` binary envelope key.

Every aggregation — classic or new — is carried in ONE ordered physical
agg list ``[[in_col, op_string, out_col], ...]`` where extended ops encode
their parameters in the op string (``"topk:5:largest"``,
``"quantile:0.95:0.01"``): the merged payload is self-describing, so the
client-side merge (:mod:`bqueryd_tpu.parallel.hostmerge`) needs no side
channel to finalize.

Control-plane module: **no JAX, no pandas** (NumPy only, for the broadcast
dimension table's columns).
"""

import os
from dataclasses import dataclass, field

import numpy as np

from bqueryd_tpu.models.query import (
    AGG_OPS,
    MERGEABLE_OPS,
    freeze_value,
    normalize_agg_list,
)
from bqueryd_tpu.utils.env import env_num

DAG_VERSION = 1

#: extended (non-classic) operator prefixes; parameters ride the op string
EXTENDED_OP_PREFIXES = ("topk", "quantile")

#: classic ops a DAG GroupAgg node may carry (``sorted_count_distinct`` is
#: excluded: its run-boundary semantics depend on the physical shard sort
#: order, which derived join/window columns do not preserve)
DAG_CLASSIC_OPS = tuple(op for op in AGG_OPS if op != "sorted_count_distinct")

#: recognized window units (value = nanoseconds)
_WINDOW_UNITS = {
    "s": 1_000_000_000,
    "m": 60 * 1_000_000_000,
    "h": 3600 * 1_000_000_000,
    "d": 86400 * 1_000_000_000,
}


class DagValidationError(ValueError):
    """A query spec the DAG compiler refuses.  ``error_class`` is the
    structured class the controller replies (client-side it lands on
    ``RPCError.error_class``): ``"UnsupportedOp"`` for unknown/illegal
    operators, ``"InvalidPlan"`` for structural problems (bad join table,
    bad window spec, colliding output names)."""

    def __init__(self, message, error_class="InvalidPlan"):
        super().__init__(message)
        self.error_class = error_class


def dag_batch_enabled():
    """The ``BQUERYD_TPU_DAG_BATCH`` kill switch (default on): batched
    shard-group dispatch + device-resident merge for extended DAG queries.
    ``0`` restores the PR-13 per-shard dispatch + host value-keyed merge
    bit-identically — it is also the mixed-version fallback (keep it set
    until every worker is >= PR-15, see MIGRATION) and the route
    count_distinct / dict-measure DAGs always take."""
    return os.environ.get("BQUERYD_TPU_DAG_BATCH", "1") != "0"


def dag_batchable(dag):
    """Whether this DAG's aggregations can ride ONE CalcMessage per shard
    group with the device-resident merge: classic mergeable ops plus the
    extended mergeable part kinds (top-k dense re-select, sketch
    bucket-count addition).  ``count_distinct`` (per-group value SETS —
    shipped, not reduce-scattered) and raw-rows keep the per-shard
    dispatch, exactly like they always have on the classic path."""
    if not dag_batch_enabled():
        return False
    if not dag.aggregate_rows:
        return False
    for _in_col, op, _out in dag.aggs:
        kind = parse_op(op)[0]
        if kind not in MERGEABLE_OPS and kind not in EXTENDED_OP_PREFIXES:
            return False
    return True


def topk_limit():
    """Per-group k ceiling (payload growth is k x groups x shards)."""
    return env_num("BQUERYD_TPU_TOPK_LIMIT", 1024, cast=int)


def join_broadcast_limit():
    """Max dimension-table rows shipped in a dispatch envelope.  The
    broadcast join serializes the whole dimension table into every
    CalcMessage; past ~1e5 rows it stops being "small" and belongs in a
    shard."""
    return env_num("BQUERYD_TPU_JOIN_BROADCAST_LIMIT", 100_000, cast=int)


def sketch_alpha():
    """Default relative accuracy of quantile sketches (DDSketch-style
    log-gamma buckets, gamma = (1+alpha)/(1-alpha)): the estimate's
    relative error vs the exact empirical quantile is <= alpha for values
    with magnitude in the sketch's bucketed range (see parallel.opexec).
    An out-of-range override degrades to the shipped default, matching the
    env contract everywhere else."""
    alpha = env_num("BQUERYD_TPU_SKETCH_ALPHA", 0.01, cast=float)
    return alpha if 0.0 < alpha < 0.5 else 0.01


# -- op strings ---------------------------------------------------------------

def make_topk_op(k, largest=True):
    return f"topk:{int(k)}:{'largest' if largest else 'smallest'}"


def make_quantile_op(q, alpha=None):
    alpha = sketch_alpha() if alpha is None else float(alpha)
    return f"quantile:{float(q)!r}:{alpha!r}"


def parse_op(op):
    """Decompose an op string: ``("sum",)`` / ``("topk", k, largest)`` /
    ``("quantile", q, alpha)``.  Raises :class:`DagValidationError` for
    malformed extended ops; classic strings pass through unparsed."""
    if not isinstance(op, str) or ":" not in op:
        return (op,)
    head, _, rest = op.partition(":")
    if head == "topk":
        parts = rest.split(":")
        try:
            k = int(parts[0])
            largest = {"largest": True, "smallest": False}[parts[1]]
        except (IndexError, KeyError, ValueError):
            raise DagValidationError(
                f"malformed topk op {op!r} (want 'topk:<k>:largest|smallest')",
                error_class="UnsupportedOp",
            ) from None
        return ("topk", k, largest)
    if head == "quantile":
        parts = rest.split(":")
        try:
            q = float(parts[0])
            alpha = float(parts[1]) if len(parts) > 1 else sketch_alpha()
        except (IndexError, ValueError):
            raise DagValidationError(
                f"malformed quantile op {op!r} (want 'quantile:<q>[:<alpha>]')",
                error_class="UnsupportedOp",
            ) from None
        return ("quantile", q, alpha)
    return (op,)


def is_extended_op(op):
    return isinstance(op, str) and op.partition(":")[0] in EXTENDED_OP_PREFIXES


# -- node types ---------------------------------------------------------------

@dataclass
class Scan:
    """Fact-table scan: the shard files plus the predicate conjunction that
    pushes down to them (plan-time shard pruning + in-scan masking)."""
    filenames: list
    pushdown: list = field(default_factory=list)


@dataclass
class HashJoinBroadcast:
    """Broadcast hash join of a small dimension table (inner).

    ``table`` is ``{col: np.ndarray}`` with unique values in ``right_on``;
    the fact side factorizes ``on`` and probes as a gather (one lookup per
    DISTINCT key, one gather per row).  Rows whose key is absent from the
    dimension table are dropped (inner-join semantics — document per the
    README's join size/shape limits)."""
    on: str
    right_on: str
    table: dict
    select: list = field(default_factory=list)

    def n_rows(self):
        return len(next(iter(self.table.values()))) if self.table else 0


@dataclass
class WindowRollup:
    """Datetime-bucket derived group key: ``alias`` = ``column`` floored to
    ``every_ns`` boundaries (epoch-anchored plus ``origin_ns``).  NaT rows
    carry a null key and drop from the rollup, like any null group key."""
    column: str
    every_ns: int
    alias: str
    origin_ns: int = 0


@dataclass
class Filter:
    """Post-derivation filter: terms that reference join-selected or
    window-derived columns, evaluated AFTER those nodes run.  Fact-column
    terms belong in the scan pushdown instead (prunable)."""
    terms: list = field(default_factory=list)


@dataclass
class GroupAgg:
    """The classic mergeable aggregation stage (existing kernels,
    unchanged): every ``[in, op, out]`` with a classic op."""
    keys: list
    aggs: list = field(default_factory=list)


@dataclass
class TopK:
    """Per-group top-k of one measure, via the sort route.  Partial =
    per-shard top-k (flat values/offsets), merge = k-way re-select —
    mergeable, bounded at k x groups values per payload."""
    in_col: str
    out_col: str
    k: int
    largest: bool = True


@dataclass
class QuantileSketch:
    """Mergeable per-group quantile sketch: DDSketch-style log-gamma
    buckets (gamma = (1+alpha)/(1-alpha)) whose cross-shard/worker merge
    is bucket-count addition; the estimate carries <= alpha relative error
    vs the exact empirical quantile (lower order statistic)."""
    in_col: str
    out_col: str
    q: float
    alpha: float


_NODE_KINDS = {
    "scan": Scan,
    "join": HashJoinBroadcast,
    "window": WindowRollup,
    "filter": Filter,
    "group": GroupAgg,
    "topk": TopK,
    "quantile": QuantileSketch,
}


@dataclass
class OperatorDAG:
    """The compiled operator DAG of one query.

    Structurally the pipeline is ``scan -> [join] -> [window] -> [filter]
    -> group stage``, with the group stage fanning out to one
    :class:`GroupAgg` node (all classic ops) plus one :class:`TopK` /
    :class:`QuantileSketch` node per extended aggregation; ``nodes()`` /
    ``edges()`` materialize that graph for validation and explain.  The
    ordered ``aggs`` list (``[[in, op_string, out], ...]``) is the output
    contract: payload agg order, finalize order, and the wire op strings.
    """
    scan: Scan
    group_keys: list
    aggs: list                              # ordered [[in, op_string, out]]
    join: HashJoinBroadcast = None
    window: WindowRollup = None
    filter: Filter = None
    aggregate_rows: bool = True             # False = raw-rows (plain only)
    expand_filter_column: str = None        # plain-groupby passthrough
    sole_payload: bool = False              # plain-groupby passthrough

    # -- structure ----------------------------------------------------------
    def nodes(self):
        """``{node_id: node}`` in pipeline order; agg-stage nodes are
        ``group`` plus ``topk:<out>`` / ``quantile:<out>`` per extended
        aggregation."""
        out = {"scan": self.scan}
        if self.join is not None:
            out["join"] = self.join
        if self.window is not None:
            out["window"] = self.window
        if self.filter is not None and self.filter.terms:
            out["filter"] = self.filter
        classic = [a for a in self.aggs if not is_extended_op(a[1])]
        out["group"] = GroupAgg(keys=list(self.group_keys), aggs=classic)
        for in_col, op, out_col in self.aggs:
            parsed = parse_op(op)
            if parsed[0] == "topk":
                out[f"topk:{out_col}"] = TopK(
                    in_col, out_col, parsed[1], parsed[2]
                )
            elif parsed[0] == "quantile":
                out[f"quantile:{out_col}"] = QuantileSketch(
                    in_col, out_col, parsed[1], parsed[2]
                )
        return out

    def edges(self):
        """``[(src_id, dst_id), ...]``: the linear derivation spine plus the
        group stage's fan-out to each per-aggregation node."""
        nodes = self.nodes()
        spine = [
            nid for nid in ("scan", "join", "window", "filter")
            if nid in nodes
        ]
        edges = list(zip(spine, spine[1:]))
        last = spine[-1]
        for nid in nodes:
            if nid == "group" or nid.startswith(("topk:", "quantile:")):
                edges.append((last, nid))
        return edges

    def is_plain(self):
        """True when this DAG is exactly the historical groupby shape —
        no join, no window, no post-derivation filter, classic ops only."""
        return (
            self.join is None
            and self.window is None
            and (self.filter is None or not self.filter.terms)
            and not any(is_extended_op(a[1]) for a in self.aggs)
        )

    def plain_groupby_query(self):
        """The exact :class:`GroupByQuery` a plain DAG round-trips to (None
        for extended shapes).  The round trip is field-for-field — the
        worker executes plain DAGs through the UNCHANGED engine path, so
        plain groupbys stay bit-identical (and result-cache-compatible)
        with the pre-DAG sequence."""
        if not self.is_plain():
            return None
        from bqueryd_tpu.models.query import GroupByQuery

        return GroupByQuery(
            list(self.group_keys),
            [list(a) for a in self.aggs],
            [tuple(t) for t in self.scan.pushdown],
            aggregate=self.aggregate_rows,
            expand_filter_column=self.expand_filter_column,
            sole_payload=self.sole_payload,
        )

    # -- identity -----------------------------------------------------------
    def derive_signature(self):
        """Hashable identity of the DERIVATION pipeline alone — everything
        that shapes the folded group codes and derived columns (group keys,
        pushdown, join content, window geometry, post-derivation filter)
        but NOT the agg list.  This is the content key the mesh fast path's
        working-set entries (join-probe gathers, window-bucket keys, the
        folded composite codes) live under: two DAG queries differing only
        in measures/aggs share one decode/align/H2D pass."""
        full = self.signature()
        # ("dag", version, group_keys, aggs, pushdown, filter, join, window,
        #  aggregate_rows, expand, sole) — drop the agg list (index 3)
        return full[:3] + full[4:]

    def signature(self):
        """Hashable identity (result-cache key component; folded into the
        logical plan's signature so DAG queries never dedup-fuse with a
        plain groupby over the same fact projection)."""
        join_sig = None
        if self.join is not None:
            join_sig = (
                self.join.on,
                self.join.right_on,
                tuple(sorted(
                    (c, freeze_value(np.asarray(v)))
                    for c, v in self.join.table.items()
                )),
                tuple(self.join.select),
            )
        window_sig = None
        if self.window is not None:
            window_sig = (
                self.window.column, int(self.window.every_ns),
                self.window.alias, int(self.window.origin_ns),
            )
        return (
            "dag", DAG_VERSION,
            tuple(self.group_keys),
            freeze_value(self.aggs),
            freeze_value([tuple(t) for t in self.scan.pushdown]),
            freeze_value(
                [tuple(t) for t in (self.filter.terms if self.filter else [])]
            ),
            join_sig,
            window_sig,
            bool(self.aggregate_rows),
            self.expand_filter_column,
            bool(self.sole_payload),
        )

    def explain(self):
        lines = [f"OperatorDAG v{DAG_VERSION}"]
        for nid, node in self.nodes().items():
            lines.append(f"  {nid}: {type(node).__name__} {node}")
        lines.append(f"  edges: {self.edges()}")
        return "\n".join(lines)

    # -- wire form ----------------------------------------------------------
    def to_wire(self):
        wire = {
            "v": DAG_VERSION,
            "filenames": list(self.scan.filenames),
            "pushdown": [list(t) for t in self.scan.pushdown],
            "group_keys": list(self.group_keys),
            "aggs": [list(a) for a in self.aggs],
            "aggregate_rows": bool(self.aggregate_rows),
            "expand_filter_column": self.expand_filter_column,
            "sole": bool(self.sole_payload),
        }
        if self.filter is not None and self.filter.terms:
            wire["filter"] = [list(t) for t in self.filter.terms]
        if self.join is not None:
            wire["join"] = {
                "on": self.join.on,
                "right_on": self.join.right_on,
                "table": {
                    c: np.asarray(v) for c, v in self.join.table.items()
                },
                "select": list(self.join.select),
            }
        if self.window is not None:
            wire["window"] = {
                "column": self.window.column,
                "every_ns": int(self.window.every_ns),
                "alias": self.window.alias,
                "origin_ns": int(self.window.origin_ns),
            }
        return wire

    @classmethod
    def from_wire(cls, wire):
        if wire.get("v") != DAG_VERSION:
            raise DagValidationError(
                f"unknown DAG version {wire.get('v')!r} (this worker speaks "
                f"v{DAG_VERSION}; see MIGRATION 'PR 13')"
            )
        join = None
        if wire.get("join"):
            j = wire["join"]
            join = HashJoinBroadcast(
                on=j["on"], right_on=j["right_on"],
                table={c: np.asarray(v) for c, v in j["table"].items()},
                select=list(j["select"]),
            )
        window = None
        if wire.get("window"):
            w = wire["window"]
            window = WindowRollup(
                column=w["column"], every_ns=int(w["every_ns"]),
                alias=w["alias"], origin_ns=int(w.get("origin_ns", 0)),
            )
        dag = cls(
            scan=Scan(
                filenames=list(wire["filenames"]),
                pushdown=[tuple(t) for t in wire.get("pushdown", [])],
            ),
            group_keys=list(wire["group_keys"]),
            aggs=[list(a) for a in wire["aggs"]],
            join=join,
            window=window,
            filter=Filter(
                terms=[tuple(t) for t in wire.get("filter", [])]
            ) if wire.get("filter") else None,
            aggregate_rows=bool(wire.get("aggregate_rows", True)),
            expand_filter_column=wire.get("expand_filter_column"),
            sole_payload=bool(wire.get("sole")),
        )
        validate_dag(dag)
        return dag


# -- validation ---------------------------------------------------------------

def parse_window_every(every):
    """``"1h"`` / ``"30m"`` / ``"90s"`` / ``"1d"`` (or a plain number of
    seconds) -> nanoseconds."""
    if isinstance(every, (int, float)) and not isinstance(every, bool):
        ns = int(float(every) * 1_000_000_000)
    elif isinstance(every, str) and every[-1:] in _WINDOW_UNITS:
        try:
            ns = int(float(every[:-1]) * _WINDOW_UNITS[every[-1]])
        except ValueError:
            raise DagValidationError(
                f"malformed window every {every!r}"
            ) from None
    else:
        raise DagValidationError(
            f"malformed window every {every!r} (want e.g. '1h', '30m', "
            f"'90s', '1d', or seconds)"
        )
    if ns <= 0:
        raise DagValidationError(f"window every must be positive, got {every!r}")
    return ns


def validate_dag(dag):
    """Typed validation of a compiled DAG; raises
    :class:`DagValidationError`.  Checks everything resolvable without the
    fact schema (fact-column existence is validated at shard-open time by
    the executor, which has the table)."""
    derived = set()
    if dag.join is not None:
        j = dag.join
        if not j.table or j.right_on not in j.table:
            raise DagValidationError(
                f"join table must contain the join key {j.right_on!r}"
            )
        lengths = {len(np.asarray(v)) for v in j.table.values()}
        if len(lengths) != 1:
            raise DagValidationError("join table columns have unequal lengths")
        n = j.n_rows()
        if n == 0:
            raise DagValidationError("join table is empty")
        limit = join_broadcast_limit()
        if n > limit:
            raise DagValidationError(
                f"join table has {n} rows, above the broadcast limit {limit} "
                f"(BQUERYD_TPU_JOIN_BROADCAST_LIMIT); store it as a shard "
                f"instead"
            )
        keys = np.asarray(j.table[j.right_on])
        if len(np.unique(keys)) != len(keys):
            raise DagValidationError(
                f"join key {j.right_on!r} has duplicate values: the "
                f"broadcast hash join requires a unique dimension key"
            )
        missing = [c for c in j.select if c not in j.table]
        if missing:
            raise DagValidationError(
                f"join select columns absent from the table: {missing}"
            )
        if j.on in j.select:
            raise DagValidationError(
                f"join select column {j.on!r} collides with the fact join key"
            )
        derived.update(j.select)
    if dag.window is not None:
        if dag.window.every_ns <= 0:
            raise DagValidationError("window every_ns must be positive")
        if dag.window.alias in derived:
            raise DagValidationError(
                f"window alias {dag.window.alias!r} collides with a "
                f"join-selected column"
            )
        if dag.window.alias == dag.window.column:
            raise DagValidationError(
                "window alias must differ from its source column"
            )
        derived.add(dag.window.alias)
    if not dag.aggregate_rows and not dag.is_plain():
        raise DagValidationError(
            "aggregate=False (raw rows) is only supported for plain "
            "filter->groupby shapes",
            error_class="UnsupportedOp",
        )
    out_names = list(dag.group_keys) + [a[2] for a in dag.aggs]
    if len(set(out_names)) != len(out_names):
        raise DagValidationError(
            f"output column names collide: {out_names}"
        )
    if dag.aggregate_rows and not dag.group_keys:
        raise DagValidationError("groupby keys must not be empty")
    for in_col, op, _out in dag.aggs:
        parsed = parse_op(op)
        kind = parsed[0]
        if kind == "topk":
            k = parsed[1]
            if not 1 <= k <= topk_limit():
                raise DagValidationError(
                    f"topk k={k} outside [1, {topk_limit()}] "
                    f"(BQUERYD_TPU_TOPK_LIMIT)",
                    error_class="UnsupportedOp",
                )
        elif kind == "quantile":
            q, alpha = parsed[1], parsed[2]
            if not 0.0 < q < 1.0:
                raise DagValidationError(
                    f"quantile q={q} outside (0, 1)",
                    error_class="UnsupportedOp",
                )
            if not 0.0 < alpha < 0.5:
                raise DagValidationError(
                    f"quantile alpha={alpha} outside (0, 0.5)",
                    error_class="UnsupportedOp",
                )
        elif kind not in DAG_CLASSIC_OPS:
            raise DagValidationError(
                f"unsupported aggregation op {op!r} on {in_col!r}; "
                f"supported: {DAG_CLASSIC_OPS + EXTENDED_OP_PREFIXES}",
                error_class="UnsupportedOp",
            )
    return dag


# -- compilation --------------------------------------------------------------

def dag_from_query(query, filenames=()):
    """Plain :class:`GroupByQuery` -> DAG.  The inverse of
    :meth:`OperatorDAG.plain_groupby_query`; the pair is an exact field
    round trip (asserted over the fuzz corpus), which is what lets the
    worker compile EVERY groupby through the DAG layer while plain shapes
    keep executing on the unchanged engine."""
    return OperatorDAG(
        scan=Scan(
            filenames=list(filenames),
            pushdown=[tuple(t) for t in (query.where_terms or [])],
        ),
        group_keys=list(query.groupby_cols),
        aggs=[list(a) for a in query.agg_list],
        aggregate_rows=bool(query.aggregate),
        expand_filter_column=query.expand_filter_column,
        sole_payload=bool(query.sole_payload),
    )


def compile_query(spec):
    """The ``rpc.query`` verb's compiler: spec dict -> validated DAG.

    Spec shape (see README "Relational operators")::

        {
          "table": "facts.bcolz" | ["s0.bcolz", ...],
          "groupby": ["region",
                      {"window": {"on": "ts", "every": "1h",
                                  "alias": "ts_hour"}}],
          "aggs": [["amount", "sum", "total"],
                   ["amount", "topk", "top3", {"k": 3, "largest": True}],
                   ["amount", "quantile", "p95", {"q": 0.95}]],
          "where": [["amount", ">", 0], ["region", "==", "emea"]],
          "join": {"table": {"cust": [...], "region": [...]},
                   "on": "cust", "select": ["region"]},
        }

    ``where`` terms are split automatically: terms on fact columns push
    down to the scan (prunable against advertised shard stats), terms on
    join-selected / window-derived columns become the post-derivation
    filter node.
    """
    if not isinstance(spec, dict):
        raise DagValidationError("query spec must be a dict")
    unknown = set(spec) - {"table", "groupby", "aggs", "where", "join"}
    if unknown:
        raise DagValidationError(f"unknown query spec keys: {sorted(unknown)}")
    filenames = spec.get("table")
    if isinstance(filenames, str):
        filenames = [filenames]
    if not filenames:
        raise DagValidationError("query spec needs a 'table'")
    filenames = list(dict.fromkeys(filenames))

    join = None
    if spec.get("join") is not None:
        j = spec["join"]
        if not isinstance(j, dict) or "table" not in j or "on" not in j:
            raise DagValidationError(
                "join spec needs {'table': {col: values}, 'on': fact_col}"
            )
        table = {c: np.asarray(v) for c, v in j["table"].items()}
        right_on = j.get("right_on", j["on"])
        select = list(j.get("select", [c for c in table if c != right_on]))
        join = HashJoinBroadcast(
            on=j["on"], right_on=right_on, table=table, select=select
        )

    window = None
    group_keys = []
    for entry in spec.get("groupby") or []:
        if isinstance(entry, str):
            group_keys.append(entry)
            continue
        if isinstance(entry, dict) and "window" in entry:
            if window is not None:
                raise DagValidationError(
                    "at most one window rollup per query"
                )
            w = entry["window"]
            if not isinstance(w, dict) or "on" not in w or "every" not in w:
                raise DagValidationError(
                    "window spec needs {'on': datetime_col, 'every': '1h'}"
                )
            every_ns = parse_window_every(w["every"])
            alias = w.get("alias") or f"{w['on']}_{w['every']}"
            origin_ns = int(w.get("origin_ns", 0))
            window = WindowRollup(
                column=w["on"], every_ns=every_ns, alias=alias,
                origin_ns=origin_ns,
            )
            group_keys.append(alias)
            continue
        raise DagValidationError(f"malformed groupby entry {entry!r}")

    aggs = []
    for agg in spec.get("aggs") or []:
        agg = list(agg)
        if len(agg) == 4 and isinstance(agg[3], dict):
            in_col, op, out_col, params = agg
            if op == "topk":
                op = make_topk_op(
                    params.get("k", 1), params.get("largest", True)
                )
            elif op == "quantile":
                if "q" not in params:
                    raise DagValidationError(
                        "quantile agg needs params {'q': <0..1>}",
                        error_class="UnsupportedOp",
                    )
                op = make_quantile_op(params["q"], params.get("alpha"))
            else:
                raise DagValidationError(
                    f"op {op!r} takes no params dict",
                    error_class="UnsupportedOp",
                )
            aggs.append([in_col, op, out_col])
        elif len(agg) == 3:
            aggs.append([agg[0], agg[1], agg[2]])
        else:
            raise DagValidationError(
                f"malformed agg {agg!r} (want [in, op, out] or "
                f"[in, op, out, params])"
            )
    if not aggs:
        raise DagValidationError("query spec needs at least one agg")
    # classic shorthand normalization on the classic subset only
    aggs = [
        a if is_extended_op(a[1]) else normalize_agg_list([a])[0]
        for a in aggs
    ]

    derived = set(join.select) if join is not None else set()
    if window is not None:
        derived.add(window.alias)
    pushdown, post = [], []
    for term in spec.get("where") or []:
        term = tuple(term)
        if len(term) != 3:
            raise DagValidationError(f"malformed where term {term!r}")
        (post if term[0] in derived else pushdown).append(term)

    dag = OperatorDAG(
        scan=Scan(filenames=filenames, pushdown=pushdown),
        group_keys=group_keys,
        aggs=aggs,
        join=join,
        window=window,
        filter=Filter(terms=post) if post else None,
    )
    validate_dag(dag)
    return dag


def groupby_equivalent(dag):
    """The groupby-shaped ``(LogicalPlan, kwargs)`` the controller's
    existing machinery dispatches: the plan carries the fact-side scan /
    pushdown (shard pruning works unchanged), the ordered physical agg
    list (extended op strings included), and the DAG signature folded into
    the plan signature (dedup/supersede can never confuse a DAG query with
    a plain groupby of the same projection).  ``kwargs`` carries the wire
    DAG under ``"dag"`` plus the batching eligibility: device-mergeable
    part kinds (classic + top-k + sketch) ship ONE CalcMessage per shard
    group — the same ``_shard_groups`` path, failover and hedging
    semantics as plain groupbys — while count_distinct / raw-rows shapes
    (and everything under ``BQUERYD_TPU_DAG_BATCH=0``) keep the PR-13
    per-shard dispatch with the host value-keyed merge."""
    from bqueryd_tpu.plan.logical import plan_groupby

    plan = plan_groupby(
        list(dag.scan.filenames),
        list(dag.group_keys),
        [list(a) for a in dag.aggs],
        [list(t) for t in dag.scan.pushdown],
        aggregate=dag.aggregate_rows,
    )
    plan.dag_sig = dag.signature()
    return plan, {"batch": dag_batchable(dag), "dag": dag.to_wire()}
