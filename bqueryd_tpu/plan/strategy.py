"""Cost-based kernel-strategy selection from per-shard statistics.

``ops.groupby.partial_tables`` has three physical routes for the mergeable
aggregations:

* ``matmul``  — the MXU one-hot limb-matmul (rides the systolic array; wins
  up to ``BQUERYD_TPU_MATMUL_GROUPS`` groups, loses badly when emulated on a
  CPU backend — the 6x regression BENCH_r05 measured for the forced route);
* ``scatter`` — blocked exact-int32 segment scatters (the default past the
  matmul group ceiling);
* ``sort``    — sort + prefix-diff reduction, whose cost is independent of
  the group count (takes over when the scatter's ``blocks x groups`` table
  outgrows HBM economics).

Until this subsystem the route was chosen at kernel-dispatch time from the
ACTUAL factorized cardinality — correct, but only after every shard was
dispatched and decoded.  The planner chooses per dispatch from advertised
stats instead, and the hint travels in the plan fragment.

The hint is ADVISORY by design: ``partial_tables`` keeps every safety guard.
In particular a ``matmul`` hint still passes through ``_matmul_profitable``,
whose CPU-emulation guard stands — the planner path can never reproduce the
forced-matmul regression, because forcing is exactly what a hint cannot do.
With no stats (cold shard, no sidecar yet) the selector returns ``auto``:
identical behaviour to the pre-planner static route.

Group-cardinality estimation: per key column, shards whose [min, max] ranges
overlap are assumed to share a key domain (their global cardinality is the
max per-shard cardinality — the iid-sharding case); pairwise-disjoint ranges
sum (range-partitioned data).  Multi-key spaces multiply per-column
estimates, capped by the row count.

Control-plane module: no JAX imports.  The two env knobs it reads mirror
``ops.groupby`` (``BQUERYD_TPU_MATMUL_GROUPS``, ``BQUERYD_TPU_MATMUL_CELLS``)
— duplicated here rather than imported because ``ops`` pulls in JAX.
"""

import os

STRATEGY_AUTO = "auto"
STRATEGY_HOST = "host"
STRATEGY_MATMUL = "matmul"
STRATEGY_SCATTER = "scatter"
STRATEGY_SORT = "sort"
#: calibration-backed matmul: binding INSIDE the kernel guards — the worker
#: skips only the op/dtype profitability heuristic, while the backend guard
#: and the ``matmul_groups_limit``/``matmul_cells_limit`` value guards stand
#: (so the forced-matmul regression stays unreachable).  Only emitted by
#: :func:`select_calibrated` when measurement backs the matmul route.
STRATEGY_MATMUL_BINDING = "matmul!"

STRATEGIES = (
    STRATEGY_AUTO, STRATEGY_HOST, STRATEGY_MATMUL, STRATEGY_SCATTER,
    STRATEGY_SORT, STRATEGY_MATMUL_BINDING,
)

#: mirrors ops.groupby._SUM_BLOCK / _MAX_BLOCK_SEGMENTS: the blocked scatter
#: materializes ceil(rows / 65536) x groups buckets and stops paying for
#: itself past 2^25 of them
_SUM_BLOCK = 65536
_MAX_BLOCK_SEGMENTS = 1 << 25


def matmul_groups_limit():
    """JAX-free mirror of ``ops.groupby.matmul_groups_limit``."""
    return int(os.environ.get("BQUERYD_TPU_MATMUL_GROUPS", 8192))


def matmul_cells_limit():
    """JAX-free mirror of ``ops.groupby._matmul_cells_limit``."""
    return int(os.environ.get("BQUERYD_TPU_MATMUL_CELLS", 1 << 36))


def _column_card_estimate(stats_list, column):
    """Estimated global distinct count of ``column`` across a shard group,
    or None when any shard lacks the cardinality.  Overlapping value ranges
    -> shared domain (max); disjoint ranges -> partitioned domain (sum)."""
    cards, ranges = [], []
    for stats in stats_list:
        entry = ((stats or {}).get("cols") or {}).get(column)
        if not entry or "card" not in entry:
            return None
        cards.append(int(entry["card"]))
        if entry.get("min") is not None and entry.get("max") is not None:
            ranges.append((entry["min"], entry["max"]))
    if not cards:
        return None
    if len(ranges) == len(cards) and len(ranges) > 1:
        ordered = sorted(ranges)
        disjoint = all(
            ordered[i][1] < ordered[i + 1][0] for i in range(len(ordered) - 1)
        )
        if disjoint:
            return sum(cards)
    return max(cards)


def estimate_groups(stats_list, groupby_cols):
    """Estimated group count of a query over a shard group, or None when the
    stats cannot support an estimate (some shard or key column unknown)."""
    if not stats_list or any(s is None for s in stats_list):
        return None
    total_rows = sum(int(s.get("rows", 0)) for s in stats_list)
    est = 1
    for col in groupby_cols:
        card = _column_card_estimate(stats_list, col)
        if card is None:
            return None
        est *= max(card, 1)
        if est >= total_rows:
            return max(total_rows, 1)  # cannot exceed the row count
    return max(est, 1)


def choose_strategy(total_rows, est_groups):
    """Pick a kernel route from (rows, estimated groups); ``auto`` when the
    estimate is missing or the economics are ambiguous."""
    if est_groups is None or total_rows is None or total_rows <= 0:
        return STRATEGY_AUTO
    limit = matmul_groups_limit()
    if 0 < est_groups <= limit and total_rows * est_groups <= matmul_cells_limit():
        # low cardinality: the MXU one-hot contraction wins where available;
        # partial_tables still applies its backend guard (advisory hint)
        return STRATEGY_MATMUL
    if est_groups > limit:
        blocks = -(-total_rows // _SUM_BLOCK)
        if blocks * est_groups > _MAX_BLOCK_SEGMENTS:
            # the blocked scatter table would outgrow its HBM budget: the
            # sort + prefix-diff reduction is group-count-independent
            return STRATEGY_SORT
        return STRATEGY_SCATTER
    return STRATEGY_AUTO


def select_for_group(stats_by_file, filenames, groupby_cols):
    """Controller entry point: HEURISTIC strategy hint for one dispatch
    group.  Returns ``(strategy, est_groups, total_rows)``.  Malformed
    advertised stats (version-skewed worker) degrade to ``auto``, never
    raise — a stats problem must not fail the query it was meant to speed
    up.  This is the PR-5 behaviour, bit for bit; the calibrated layer
    (:func:`select_calibrated`) wraps it and falls back here whenever
    calibration is disabled or cold."""
    stats_list = [
        (stats_by_file or {}).get(f) for f in filenames
    ]
    if any(not isinstance(s, dict) for s in stats_list):
        return STRATEGY_AUTO, None, None
    try:
        total_rows = sum(int(s.get("rows", 0)) for s in stats_list)
        est = estimate_groups(stats_list, groupby_cols)
        return choose_strategy(total_rows, est), est, total_rows
    except (TypeError, ValueError):
        return STRATEGY_AUTO, None, None


def candidate_strategies(total_rows, est_groups):
    """The kernel routes LEGAL at (rows, est groups): scatter and sort are
    always-correct fallbacks; matmul is a candidate only inside the same
    value guards ``ops.partial_tables`` enforces (group ceiling, cells
    budget) — calibration may only rank routes the guards would accept, so
    a measured preference can never smuggle an illegal route past them."""
    candidates = [STRATEGY_SCATTER, STRATEGY_SORT]
    if (
        est_groups is not None
        and total_rows is not None
        and 0 < est_groups <= matmul_groups_limit()
        and total_rows * est_groups <= matmul_cells_limit()
    ):
        candidates.insert(0, STRATEGY_MATMUL)
    return tuple(candidates)


def select_calibrated(stats_by_file, filenames, groupby_cols,
                      calibration=None):
    """Measured-cost strategy selection: the heuristic choice refined by a
    :class:`~bqueryd_tpu.plan.calibrate.CalibrationStore` when one is given
    and warm.  Returns ``(strategy, est_groups, total_rows, reason)`` with
    ``reason`` from ``CalibrationStore.choose`` (``cold`` also covers every
    disabled/degraded path).  Decision ladder:

    * no stats / calibration off / cold bucket -> the heuristic, unchanged
      (cold start is bit-identical to :func:`select_for_group`);
    * measurement ranks a route best among the LEGAL candidates -> that
      route; a measured-or-agreeing ``matmul`` is promoted to
      :data:`STRATEGY_MATMUL_BINDING` (binding inside the kernel guards);
    * the deterministic epsilon slot explores an unmeasured legal candidate
      as an ADVISORY hint — exploration never emits the binding form.
    """
    from bqueryd_tpu.plan import calibrate

    strategy, est, total_rows = select_for_group(
        stats_by_file, filenames, groupby_cols
    )
    if (
        calibration is None
        or not calibrate.enabled()
        or est is None
        or total_rows is None
        or strategy not in (STRATEGY_MATMUL, STRATEGY_SCATTER, STRATEGY_SORT)
    ):
        return strategy, est, total_rows, "cold"
    choice, reason = calibration.choose(
        total_rows, est, None, candidate_strategies(total_rows, est),
        strategy,
    )
    if choice == STRATEGY_MATMUL and reason in ("measured", "agree"):
        # measurement backs the MXU route (reason "prior" — an analytic
        # extrapolation with zero matmul walls — stays advisory): binding
        # inside the guards (only the op/dtype profitability heuristic
        # yields; backend + value guards still stand at the kernel)
        choice = STRATEGY_MATMUL_BINDING
    return choice, est, total_rows, reason
