"""Logical query plans: the typed form of a ``groupby`` RPC.

The reference (and the port until this subsystem) fanned the RPC verb out
verbatim: every shard received the raw ``(filenames, groupby_cols, agg_list,
where_terms)`` tuple and every route decision happened at kernel-dispatch
time.  A :class:`LogicalPlan` makes the query a first-class object the
control plane can reason about *before* anything is dispatched:

* **compile** — ``compile_groupby`` turns the RPC arguments into a small node
  pipeline ``Scan -> Filter -> GroupBy -> Aggregate -> Project`` with the
  same normalization rules as :class:`bqueryd_tpu.models.query.GroupByQuery`;
* **rewrite** — ``rewrite_plan`` applies rule passes:
  ``predicate_pushdown`` moves filter terms into the scan node (the terms
  become the scan's pruning predicate, enabling plan-time shard elimination
  against advertised min/max stats), and ``mean_decomposition`` lowers
  ``mean`` into the primitive ``sum`` + ``count`` partials plus a divide in
  the project node — the algebraic identity that makes shard partials
  mergeable (it is also exactly what the kernels compute physically, so the
  rewrite documents and deduplicates rather than changes the wire math);
* **fragment** — ``fragment_for`` cuts the per-dispatch slice of the plan (a
  shard group, a kernel-strategy hint, the sole-payload flag) into a small
  pickle-friendly dict a :class:`~bqueryd_tpu.messages.CalcMessage` carries
  under its ``plan`` binary field; ``fragment_to_query`` rebuilds the
  worker-side :class:`GroupByQuery` from it.

This module is control-plane code: **no JAX, no pandas** — the controller
imports it freely.
"""

from dataclasses import dataclass, field

# the ONE copy of the agg shorthand rules (JAX-free), shared with the
# worker's GroupByQuery so plan signatures and executed queries can never
# normalize differently
from bqueryd_tpu.models.query import freeze_value, normalize_agg_list

PLAN_VERSION = 1


@dataclass
class ScanNode:
    filenames: list
    columns: list                       # every column the query touches
    pushdown: list = field(default_factory=list)  # where terms pushed down


@dataclass
class FilterNode:
    terms: list = field(default_factory=list)


@dataclass
class GroupByNode:
    keys: list = field(default_factory=list)


@dataclass
class AggregateNode:
    #: [[in_col, op, slot], ...] — primitive partials after rewriting
    aggs: list = field(default_factory=list)


@dataclass
class ProjectNode:
    #: ordered [(out_col, expr)]; expr is ("slot", name) or
    #: ("div", numerator_slot, denominator_slot)
    exprs: list = field(default_factory=list)


@dataclass
class LogicalPlan:
    scan: ScanNode
    filter: FilterNode
    groupby: GroupByNode
    aggregate: AggregateNode
    project: ProjectNode
    aggregate_rows: bool = True         # the RPC ``aggregate=`` kwarg
    expand_filter_column: str = None
    rewrites: list = field(default_factory=list)  # applied rule names

    @property
    def filenames(self):
        return self.scan.filenames

    @property
    def where_terms(self):
        """Effective filter conjunction wherever the terms currently live."""
        return list(self.scan.pushdown) + list(self.filter.terms)

    # -- physical form ------------------------------------------------------
    def physical_agg_list(self):
        """The engine-facing agg list this plan computes, reconstructed from
        the (possibly rewritten) aggregate + project nodes in original output
        order.  Decomposed means come back as ``[in, 'mean', out]`` — the
        kernels' mean partial already carries (sum, count), so this IS the
        decomposed physical form on the wire."""
        by_slot = {slot: (in_col, op) for in_col, op, slot in self.aggregate.aggs}
        out = []
        for out_col, expr in self.project.exprs:
            if expr[0] == "slot":
                in_col, op = by_slot[expr[1]]
                out.append([in_col, op, out_col])
            elif expr[0] == "div":
                in_col, _op = by_slot[expr[1]]
                out.append([in_col, "mean", out_col])
            else:
                raise ValueError(f"unknown project expr {expr!r}")
        return out

    def signature(self):
        """Hashable identity of the plan MINUS the shard set: two queries with
        equal signatures over the same shard group compute identical payloads
        (the shared-dispatch fusion key in the controller).  A DAG query
        (``plan.dag``) folds the full operator-DAG signature in — its join
        table / window / post-derivation filter are invisible to the
        groupby-shaped fields, and without this a DAG query could dedup-fuse
        with a plain groupby over the same projection."""
        return (
            tuple(self.groupby.keys),
            freeze_value(self.physical_agg_list()),
            freeze_value(self.where_terms),
            bool(self.aggregate_rows),
            self.expand_filter_column,
            getattr(self, "dag_sig", None),
        )

    def explain(self):
        lines = [f"LogicalPlan (rewrites: {', '.join(self.rewrites) or 'none'})"]
        lines.append(
            f"  Scan {len(self.scan.filenames)} shard(s), "
            f"cols={self.scan.columns}, pushdown={self.scan.pushdown}"
        )
        if self.filter.terms:
            lines.append(f"  Filter {self.filter.terms}")
        lines.append(f"  GroupBy {self.groupby.keys}")
        lines.append(f"  Aggregate {self.aggregate.aggs}")
        lines.append(f"  Project {self.project.exprs}")
        return "\n".join(lines)

    # -- wire form ----------------------------------------------------------
    def to_wire(self):
        return {
            "v": PLAN_VERSION,
            "scan": {
                "filenames": list(self.scan.filenames),
                "columns": list(self.scan.columns),
                "pushdown": [list(t) for t in self.scan.pushdown],
            },
            "filter": [list(t) for t in self.filter.terms],
            "groupby": list(self.groupby.keys),
            "aggregate": [list(a) for a in self.aggregate.aggs],
            "project": [[out, list(expr)] for out, expr in self.project.exprs],
            "aggregate_rows": bool(self.aggregate_rows),
            "expand_filter_column": self.expand_filter_column,
            "rewrites": list(self.rewrites),
        }

    @classmethod
    def from_wire(cls, wire):
        if wire.get("v") != PLAN_VERSION:
            raise ValueError(f"unknown plan version {wire.get('v')!r}")
        return cls(
            scan=ScanNode(
                filenames=list(wire["scan"]["filenames"]),
                columns=list(wire["scan"]["columns"]),
                pushdown=[tuple(t) for t in wire["scan"]["pushdown"]],
            ),
            filter=FilterNode(terms=[tuple(t) for t in wire["filter"]]),
            groupby=GroupByNode(keys=list(wire["groupby"])),
            aggregate=AggregateNode(aggs=[list(a) for a in wire["aggregate"]]),
            project=ProjectNode(
                exprs=[(out, tuple(expr)) for out, expr in wire["project"]]
            ),
            aggregate_rows=wire["aggregate_rows"],
            expand_filter_column=wire.get("expand_filter_column"),
            rewrites=list(wire.get("rewrites", [])),
        )


# -- compilation -------------------------------------------------------------

def compile_groupby(filenames, groupby_cols, agg_list, where_terms=None,
                    aggregate=True, expand_filter_column=None):
    """RPC arguments -> un-rewritten LogicalPlan (call :func:`rewrite_plan`
    to optimize).  Filenames are deduplicated order-preserving, matching the
    controller's fan-out contract."""
    if isinstance(filenames, str):
        filenames = [filenames]
    filenames = list(dict.fromkeys(filenames))
    aggs = normalize_agg_list(agg_list)
    where_terms = [tuple(t) for t in (where_terms or [])]
    columns, seen = [], set()
    for col in (
        list(groupby_cols)
        + [a[0] for a in aggs]
        + [t[0] for t in where_terms]
        + ([expand_filter_column] if expand_filter_column else [])
    ):
        if col not in seen:
            seen.add(col)
            columns.append(col)
    return LogicalPlan(
        scan=ScanNode(filenames=filenames, columns=columns),
        filter=FilterNode(terms=where_terms),
        groupby=GroupByNode(keys=list(groupby_cols)),
        aggregate=AggregateNode(aggs=[list(a) + [] for a in aggs]),
        project=ProjectNode(),
        aggregate_rows=aggregate,
        expand_filter_column=expand_filter_column,
    )


def _rule_predicate_pushdown(plan):
    """Filter terms -> scan pushdown: the conjunction is evaluated inside the
    scan (masked segment reduction) and, at plan time, against per-shard
    min/max statistics to prune shards that cannot match."""
    if not plan.filter.terms:
        return False
    plan.scan.pushdown = list(plan.scan.pushdown) + list(plan.filter.terms)
    plan.filter.terms = []
    return True


def _rule_mean_decomposition(plan):
    """``mean`` -> primitive ``sum`` + ``count`` partials and a project-time
    divide; duplicate primitives over the same input column are shared."""
    raw = plan.aggregate.aggs
    slots = {}       # (in_col, op) -> slot name
    new_aggs = []
    exprs = []
    changed = False

    def slot_for(in_col, op):
        key = (in_col, op)
        if key not in slots:
            slots[key] = f"__{in_col}__{op}"
            new_aggs.append([in_col, op, slots[key]])
        else:
            nonlocal changed
            changed = True  # a primitive got shared between outputs
        return slots[key]

    for in_col, op, out_col in raw:
        if op == "mean":
            changed = True
            s = slot_for(in_col, "sum")
            c = slot_for(in_col, "count")
            exprs.append((out_col, ("div", s, c)))
        else:
            exprs.append((out_col, ("slot", slot_for(in_col, op))))
    plan.aggregate.aggs = new_aggs
    plan.project.exprs = exprs
    return changed


#: rule pipeline, applied in order by rewrite_plan
REWRITE_RULES = (
    ("predicate_pushdown", _rule_predicate_pushdown),
    ("mean_decomposition", _rule_mean_decomposition),
)


def rewrite_plan(plan):
    """Apply every rewrite rule; records the names of rules that fired.
    The project node is always materialized (identity projection when no
    mean decomposes) so ``physical_agg_list`` round-trips uniformly."""
    for name, rule in REWRITE_RULES:
        if rule(plan):
            plan.rewrites.append(name)
    if not plan.project.exprs:
        # identity projection (no aggregate at all: raw-rows query)
        plan.project.exprs = [
            (out, ("slot", out)) for _in, _op, out in plan.aggregate.aggs
        ]
    return plan


def plan_groupby(filenames, groupby_cols, agg_list, where_terms=None,
                 aggregate=True, expand_filter_column=None):
    """compile + rewrite in one call (the controller's entry point)."""
    return rewrite_plan(
        compile_groupby(
            filenames, groupby_cols, agg_list, where_terms,
            aggregate=aggregate, expand_filter_column=expand_filter_column,
        )
    )


# -- fragments ---------------------------------------------------------------

def fragment_for(plan, filenames, strategy=None, sole=False):
    """The per-dispatch slice of a plan: what ONE CalcMessage executes.
    Travels as the message's ``plan`` binary field (pickled, like params).

    The calibration-backed binding promotion ("matmul!") deliberately never
    rides the wire as a strategy VALUE: pre-calibration workers would
    reject the unknown literal at the kernel (``KERNEL_STRATEGIES``
    validation) and fail the query.  It ships as the advisory "matmul"
    plus a separate ``strategy_binding`` flag — old workers ignore the
    unknown key and degrade to the advisory semantics, which is exactly
    the mixed-version contract MIGRATION.md promises."""
    binding = strategy == "matmul!"
    return {
        "v": PLAN_VERSION,
        "filenames": list(filenames),
        "groupby_cols": list(plan.groupby.keys),
        "agg_list": plan.physical_agg_list(),
        "where_terms": [list(t) for t in plan.where_terms],
        "aggregate": bool(plan.aggregate_rows),
        "expand_filter_column": plan.expand_filter_column,
        "sole": bool(sole),
        "strategy": "matmul" if binding else strategy,
        "strategy_binding": binding,
    }


def fragment_to_query(fragment):
    """Rebuild the worker-side GroupByQuery from a plan fragment."""
    from bqueryd_tpu.models.query import GroupByQuery

    return GroupByQuery(
        list(fragment["groupby_cols"]),
        [list(a) for a in fragment["agg_list"]],
        [tuple(t) for t in fragment["where_terms"]],
        aggregate=fragment.get("aggregate", True),
        expand_filter_column=fragment.get("expand_filter_column"),
        sole_payload=bool(fragment.get("sole")),
    )
