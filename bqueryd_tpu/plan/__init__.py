"""Query planning & admission: the controller's serving-layer brain.

Four pieces, all control-plane safe (no JAX, no pandas):

* :mod:`bqueryd_tpu.plan.logical`   — typed logical plans compiled from the
  ``groupby`` RPC, with rewrite rules (predicate pushdown, mean
  decomposition) and per-dispatch plan fragments;
* :mod:`bqueryd_tpu.plan.stats`     — per-shard statistics (rows, column
  min/max, key cardinality) gathered by workers, advertised in their
  registration messages, and the stats-only shard pruning predicate;
* :mod:`bqueryd_tpu.plan.strategy`  — cost-based kernel-route selection
  (scatter vs sort+prefix-diff vs MXU limb-matmul) from those stats;
* :mod:`bqueryd_tpu.plan.calibrate` — measured-cost calibration of that
  selection: per-(rows, groups, dtype, backend, strategy) kernel walls
  recorded by workers, gossiped in WRMs, refined online
  (``BQUERYD_TPU_CALIB=0`` restores the pure heuristic);
* :mod:`bqueryd_tpu.plan.admission` — bounded priority admission queue with
  per-client quotas, deadlines, and explicit BUSY backpressure;
* :mod:`bqueryd_tpu.plan.bundle`    — shared-scan multi-query fusion: the
  admission micro-batch window (``BQUERYD_TPU_BATCH_WINDOW_MS``), the plan
  compatibility signature, and the bundle fragments whole compatible groups
  dispatch (and demultiplex) as one unit.
* :mod:`bqueryd_tpu.plan.dag`       — the typed operator DAG behind
  ``rpc.query``: broadcast hash joins, per-group top-k, mergeable quantile
  sketches, time-window rollups — compiled from query specs (and from
  plain groupbys, which round-trip bit-identically onto the engine path).

``BQUERYD_TPU_PLANNER=0`` disables plan-time pruning and strategy hints
(queries revert to the static fan-out); admission limits are controlled by
their own env knobs (see :mod:`.admission`).
"""

import os

from bqueryd_tpu.plan.admission import (  # noqa: F401
    ADMIT,
    BUSY,
    DUPLICATE,
    QUEUED,
    AdmissionController,
)
from bqueryd_tpu.plan.logical import (  # noqa: F401
    LogicalPlan,
    compile_groupby,
    fragment_for,
    fragment_to_query,
    plan_groupby,
    rewrite_plan,
)
from bqueryd_tpu.plan.stats import (  # noqa: F401
    StatsCollector,
    gather_table_stats,
    stats_can_match,
)
from bqueryd_tpu.plan.strategy import (  # noqa: F401
    STRATEGIES,
    STRATEGY_AUTO,
    STRATEGY_MATMUL_BINDING,
    candidate_strategies,
    choose_strategy,
    estimate_groups,
    select_calibrated,
    select_for_group,
)
from bqueryd_tpu.plan import bundle  # noqa: F401
from bqueryd_tpu.plan import calibrate  # noqa: F401
from bqueryd_tpu.plan import dag  # noqa: F401


def planner_enabled():
    """Plan-time pruning + strategy hints; on unless BQUERYD_TPU_PLANNER=0.
    Read per query so a live controller can be re-tuned."""
    return os.environ.get("BQUERYD_TPU_PLANNER", "1") != "0"
