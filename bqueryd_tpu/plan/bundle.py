"""Shared-scan multi-query fusion: bundle compilation for the admission
micro-batch window.

PR-1's multi-query batching fuses only *bit-identical* concurrent queries —
the same shards, the same aggs, the same filters — which real serving
traffic essentially never produces (``plan_shared_dispatches`` sat at 0
across whole bench rounds).  This module widens sharing to *compatible*
queries: same shard set after pruning and same group-key columns, while
measures and filters may differ.  A compatible group dispatched together
pays the expensive per-scan work — storage decode, key alignment/factorize,
codes H2D, measure-block upload — exactly once, and runs ONE mesh program
whose per-member partial tables merge in one collective pass
(:meth:`bqueryd_tpu.parallel.executor.MeshQueryExecutor.execute_bundle`).

The window is the admission-side knob: ``BQUERYD_TPU_BATCH_WINDOW_MS``
(default 0 = off, single-query behaviour bit-identical to before) holds
admitted groupby plans for up to that many milliseconds so concurrent
queries can land in the same flush; ``BQUERYD_TPU_BATCH_MAX`` caps the
members per flush.  Grouping happens at flush time via :func:`compat_key`;
queries that cannot fuse (raw-rows, basket expansion, non-mergeable aggs,
``batch=False``) launch individually, exactly as before.

Each bundle member keeps its own identity end to end: its trace context,
deadline, quota ticket and result envelope — the bundle fragment carries a
per-member record (:func:`bundle_fragment`) the worker demultiplexes, and a
member past its deadline is dropped from the stack, never the bundle.

Control-plane module: stdlib + models.query only (no JAX, no pandas).
"""

from bqueryd_tpu.models.query import MERGEABLE_OPS, GroupByQuery
from bqueryd_tpu.utils.env import env_num

BUNDLE_VERSION = 1


def batch_window_ms():
    """Admission micro-batch window in milliseconds; 0 (the default)
    disables staging entirely — groupby plans launch the moment they are
    admitted, bit-identical to the pre-window controller.  Read per query
    so a live controller can be re-tuned."""
    return max(env_num("BQUERYD_TPU_BATCH_WINDOW_MS", 0.0), 0.0)


def batch_max():
    """Most member queries one window flush may hold; a full window flushes
    early instead of stretching the first member's latency further."""
    return max(env_num("BQUERYD_TPU_BATCH_MAX", 16, int), 2)


def compat_key(plan, keep, kwargs):
    """The plan-compatibility signature: queries with equal keys over the
    same flush window fuse into one shared-scan bundle.  Returns None for
    queries that cannot ride a bundle (they launch individually):

    * raw-rows (``aggregate=False``) and basket-expansion queries — their
      payloads are not per-group partial tables;
    * non-mergeable aggregation ops (count_distinct family) — the stacked
      partial merge is psum-shaped;
    * ``batch=False`` callers — they asked for per-shard dispatch;
    * fully-pruned plans — nothing to scan.

    The key deliberately excludes measures, filters and deadlines (the
    whole point is fusing across them: measures dedupe into a union upload,
    filters become the stacked mask axis, deadlines stay per member) and
    includes the POST-PRUNE shard set — two queries whose filters prune to
    different shard subsets scan different data and must not share a pass.
    """
    if not keep:
        return None
    if not plan.aggregate_rows or plan.expand_filter_column:
        return None
    if not kwargs.get("batch", True):
        return None
    if any(a[1] not in MERGEABLE_OPS for a in plan.physical_agg_list()):
        return None
    return (
        tuple(keep),
        tuple(plan.groupby.keys),
        kwargs.get("affinity"),
    )


def bundle_fragment(plan, filenames, members, strategy=None, sole=False):
    """The per-dispatch slice of a BUNDLE: what one CalcMessage executes
    for a whole compatible group.  Shared fields (shard group, group-key
    columns, strategy hint) ride once; each member record carries only what
    differs — its aggs, filter conjunction, deadline, and the ``member_id``
    the reply demultiplexes on.

    ``members`` is ``[(member_id, plan, deadline), ...]``.  The "matmul!"
    binding promotion ships as advisory "matmul" + ``strategy_binding``
    exactly like :func:`bqueryd_tpu.plan.logical.fragment_for` (same
    mixed-version contract)."""
    binding = strategy == "matmul!"
    return {
        "v": BUNDLE_VERSION,
        "filenames": list(filenames),
        "groupby_cols": list(plan.groupby.keys),
        "sole": bool(sole),
        "strategy": "matmul" if binding else strategy,
        "strategy_binding": binding,
        "members": [
            {
                "member_id": member_id,
                "agg_list": member_plan.physical_agg_list(),
                "where_terms": [list(t) for t in member_plan.where_terms],
                "deadline": deadline,
            }
            for member_id, member_plan, deadline in members
        ],
    }


def bundle_to_queries(fragment):
    """Rebuild the worker-side member queries from a bundle fragment:
    ``[(member_id, deadline, GroupByQuery), ...]`` in fragment order."""
    if fragment.get("v") != BUNDLE_VERSION:
        raise ValueError(f"unknown bundle version {fragment.get('v')!r}")
    groupby_cols = list(fragment["groupby_cols"])
    sole = bool(fragment.get("sole"))
    out = []
    for member in fragment["members"]:
        out.append(
            (
                member["member_id"],
                member.get("deadline"),
                GroupByQuery(
                    list(groupby_cols),
                    [list(a) for a in member["agg_list"]],
                    [tuple(t) for t in member["where_terms"]],
                    aggregate=True,
                    sole_payload=sole,
                ),
            )
        )
    return out


def member_shares(executed_ids, walls=None):
    """Per-member accountability fractions for a bundle's shared scan:
    ``{member_id: share}`` summing to 1.0 over the executed members.

    On the per-member fallback path the worker measures each member's own
    execution wall (``walls``) and shares are proportional; on the
    one-program mesh path no per-member wall exists, so the shared scan
    splits equally — the honest prior when one kernel served everyone.
    Result-cache hits are NOT executed members (the caller reports them at
    0.0: they consumed no scan).  The controller scales the bundle reply's
    shared ``phase_timings`` by these, so a slow bundle never lands every
    member in the slow-query ring with the whole bundle's wall."""
    executed = list(executed_ids)
    if not executed:
        return {}
    if walls:
        total = sum(max(float(walls.get(m, 0.0)), 0.0) for m in executed)
        if total > 0.0 and all(
            float(walls.get(m, 0.0)) > 0.0 for m in executed
        ):
            return {
                m: round(float(walls[m]) / total, 6) for m in executed
            }
    share = round(1.0 / len(executed), 6)
    return {m: share for m in executed}


def fragment_strategy(fragment):
    """The kernel-strategy hint a bundle fragment carries, with the binding
    promotion reconstructed under the same ``BQUERYD_TPU_CALIB`` kill-switch
    contract as the single-query plan fragment."""
    strategy = fragment.get("strategy")
    if strategy in (None, "auto"):
        return None
    if strategy == "matmul" and fragment.get("strategy_binding"):
        from bqueryd_tpu.plan import calibrate

        if calibrate.enabled():
            return "matmul!"
    return strategy
