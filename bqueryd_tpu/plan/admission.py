"""Admission control: bounded queueing + backpressure for the controller.

The reference accepted every RPC unconditionally: N concurrent clients meant
N concurrent fan-outs, unbounded in-flight growth, and a dead client's work
still running to completion.  The admission controller bounds the serving
layer the way an inference frontend does:

* at most ``max_active`` plans execute concurrently;
* at most ``queue_depth`` more wait in a priority queue (priority ascending,
  then earliest deadline, then FIFO);
* at most ``client_quota`` tickets (active + queued) per client identity;
* anything beyond gets an explicit **BUSY** reply immediately — clients see
  backpressure instead of a timeout, and the controller's memory stays
  bounded;
* queued tickets whose deadline passes are expired without ever launching.

Env defaults (overridable per :class:`AdmissionController` instance):
``BQUERYD_TPU_ADMIT_MAX_ACTIVE`` (64), ``BQUERYD_TPU_ADMIT_QUEUE_DEPTH``
(256), ``BQUERYD_TPU_ADMIT_CLIENT_QUOTA`` (0 = unlimited).

Control-plane module: stdlib only.
"""

import heapq
import itertools
import time

from bqueryd_tpu.utils.env import env_num

ADMIT = "admit"
QUEUED = "queued"
BUSY = "busy"
#: the ticket is ALREADY live (a client retrying after its own timeout
#: resent the same identity): callers must not launch a second run — the
#: in-flight one will answer that identity, and its completion frees the
#: slot for the client's next retry
DUPLICATE = "duplicate"


def _env_int(name, default):
    return env_num(name, default, cast=int)


class AdmissionController:
    def __init__(self, max_active=None, queue_depth=None, client_quota=None):
        self.max_active = (
            _env_int("BQUERYD_TPU_ADMIT_MAX_ACTIVE", 64)
            if max_active is None else int(max_active)
        )
        self.queue_depth = (
            _env_int("BQUERYD_TPU_ADMIT_QUEUE_DEPTH", 256)
            if queue_depth is None else int(queue_depth)
        )
        self.client_quota = (
            _env_int("BQUERYD_TPU_ADMIT_CLIENT_QUOTA", 0)
            if client_quota is None else int(client_quota)
        )
        self._active = {}    # ticket_id -> client
        self._queued = {}    # ticket_id -> (client, priority, deadline, payload)
        self._heap = []      # (priority, deadline-or-inf, seq, ticket_id)
        self._seq = itertools.count()
        self._client_load = {}  # client -> active + queued count
        self._enqueued_at = {}  # ticket_id -> monotonic enqueue time
        #: observability hook: called with the queued-seconds of every ticket
        #: that launches from the queue (the controller wires a latency
        #: histogram here; this module stays metrics-agnostic)
        self.wait_observer = None
        #: arrival-rate tap: called as ``arrival_observer(decision,
        #: payload)`` for every NON-duplicate submission — ADMIT, QUEUED
        #: and BUSY all count (λ is *offered* load; shed load is what
        #: saturation looks like).  The controller wires the capacity
        #: model's per-class arrival window here; this module stays
        #: metrics-agnostic.
        self.arrival_observer = None
        # lifetime totals (stats()): the registry counters mirror these via
        # the controller's counters dict; kept here too so a bare
        # AdmissionController remains self-describing in tests/tools
        self.total_admitted = 0
        self.total_queued = 0
        self.total_busy = 0
        self.total_expired = 0

    # -- internals ----------------------------------------------------------
    def _charge(self, client, delta):
        n = self._client_load.get(client, 0) + delta
        if n <= 0:
            self._client_load.pop(client, None)
        else:
            self._client_load[client] = n

    # -- surface -------------------------------------------------------------
    def _notify_arrival(self, decision, payload):
        """Fire the arrival tap; an observer failure must never break
        admission (same contract as wait_observer)."""
        if self.arrival_observer is not None:
            try:
                self.arrival_observer(decision, payload)
            except Exception:
                pass
        return decision

    def submit(self, ticket_id, client, priority=0, deadline=None,
               payload=None):
        """Returns ADMIT (run now), QUEUED (held), BUSY (rejected), or
        DUPLICATE (this ticket is already active/queued — do NOT launch a
        second run for it)."""
        if ticket_id in self._active or ticket_id in self._queued:
            return DUPLICATE
        if self.client_quota > 0 and (
            self._client_load.get(client, 0) >= self.client_quota
        ):
            self.total_busy += 1
            return self._notify_arrival(BUSY, payload)
        if len(self._active) < self.max_active:
            self._active[ticket_id] = client
            self._charge(client, +1)
            self.total_admitted += 1
            return self._notify_arrival(ADMIT, payload)
        if len(self._queued) >= self.queue_depth:
            self.total_busy += 1
            return self._notify_arrival(BUSY, payload)
        entry = (
            float(priority or 0),
            float(deadline) if deadline is not None else float("inf"),
            next(self._seq),
            ticket_id,
        )
        self._queued[ticket_id] = (client, priority, deadline, payload)
        self._enqueued_at[ticket_id] = time.monotonic()
        heapq.heappush(self._heap, entry)
        self._charge(client, +1)
        self.total_queued += 1
        return self._notify_arrival(QUEUED, payload)

    def pop_ready(self, now=None):
        """Drain the queue into capacity.  Returns ``(launch, expired)``:
        payload lists of tickets to start now and tickets whose deadline
        passed while queued (already released)."""
        now = time.time() if now is None else now
        launch, expired = [], []
        while self._heap and len(self._active) < self.max_active:
            _p, _d, _seq, ticket_id = heapq.heappop(self._heap)
            item = self._queued.pop(ticket_id, None)
            if item is None:
                continue  # cancelled/expired earlier; stale heap entry
            enqueued = self._enqueued_at.pop(ticket_id, None)
            client, _priority, deadline, payload = item
            if deadline is not None and deadline <= now:
                self._charge(client, -1)
                self.total_expired += 1
                expired.append(payload)
                continue
            self._active[ticket_id] = client
            self.total_admitted += 1
            if self.wait_observer is not None and enqueued is not None:
                try:
                    self.wait_observer(
                        max(time.monotonic() - enqueued, 0.0)
                    )
                except Exception:
                    pass  # an observer must never break admission
            launch.append(payload)
        # deadline sweep for tickets stuck behind higher-priority work
        if self._queued:
            for ticket_id, item in list(self._queued.items()):
                client, _priority, deadline, payload = item
                if deadline is not None and deadline <= now:
                    self._queued.pop(ticket_id, None)
                    self._enqueued_at.pop(ticket_id, None)
                    self._charge(client, -1)
                    self.total_expired += 1
                    expired.append(payload)
        return launch, expired

    def release(self, ticket_id):
        """A plan finished (reply sent, success or abort): free its slot."""
        client = self._active.pop(ticket_id, None)
        if client is not None:
            self._charge(client, -1)
            return True
        item = self._queued.pop(ticket_id, None)
        if item is not None:
            self._enqueued_at.pop(ticket_id, None)
            self._charge(item[0], -1)
            return True
        return False

    def stats(self):
        return {
            "active": len(self._active),
            "queued": len(self._queued),
            "max_active": self.max_active,
            "queue_depth": self.queue_depth,
            "client_quota": self.client_quota,
            "clients": len(self._client_load),
            "total_admitted": self.total_admitted,
            "total_queued": self.total_queued,
            "total_busy": self.total_busy,
            "total_expired": self.total_expired,
        }
