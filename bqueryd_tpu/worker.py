"""Worker nodes: event loop base + calc / downloader / movebcolz roles.

Re-design of the reference worker stack (reference bqueryd/worker.py:43-637)
around the TPU data path: a calc worker owns the local JAX device(s), keeps a
decoded-column cache feeding HBM, and executes queries with the kernels in
:mod:`bqueryd_tpu.ops` through :class:`bqueryd_tpu.models.query.QueryEngine`.
Control-plane behaviour keeps the reference's observable contract:

* one ZeroMQ ROUTER socket with a random 8-byte hex identity, connected out
  to every controller found in the coordination store (reference
  bqueryd/worker.py:48-62,89-105);
* a WorkerRegisterMessage broadcast every ``heartbeat_interval`` seconds
  carrying the re-scanned ``*.bcolz`` / ``*.bcolzs`` data files — file
  discovery latency is bounded by this delay (reference
  bqueryd/worker.py:107-143);
* BusyMessage / DoneMessage wrapped around every piece of work, errors
  returned as ErrorMessage with a traceback (reference
  bqueryd/worker.py:168-180);
* built-in verbs kill / info / loglevel / readfile / sleep (reference
  bqueryd/worker.py:202-224);
* post-task memory watchdog: RSS above the limit stops the loop so a
  supervisor restarts the process (reference bqueryd/worker.py:232-241), plus
  a device-memory watermark check the reference has no analogue for.
"""

import gc
import importlib
import os
import signal
import socket as socket_mod
import sys
import threading
import time
import traceback

import zmq

from bqueryd_tpu.utils import devicehealth

import bqueryd_tpu
from bqueryd_tpu import chaos, messages
from bqueryd_tpu.coordination import chaos_store, coordination_store
from bqueryd_tpu.messages import (
    BusyMessage,
    DoneMessage,
    ErrorMessage,
    StopMessage,
    TicketDoneMessage,
    WorkerRegisterMessage,
    msg_factory,
)
from bqueryd_tpu.utils.net import get_my_ip
from bqueryd_tpu.utils.tracing import PhaseTimer

DEFAULT_HEARTBEAT_INTERVAL = 20.0   # WRM re-broadcast / rescan period
DEFAULT_POLL_TIMEOUT = 1.0          # seconds per zmq poll tick
DEFAULT_MEMORY_LIMIT_MB = 2048      # RSS suicide threshold
#: min seconds between post-task gc.collect calls (the reference collected
#: after every task, reference bqueryd/worker.py:226; see handle())
DEFAULT_GC_INTERVAL = 10.0
DOWNLOAD_DELAY = 5.0                # downloader ticket poll period
SHARD_EXTENSIONS = (".bcolz", ".bcolzs")


class WorkerBase:
    workertype = "worker"
    #: chaos wedge latch (worker.execute "wedge" action): advertised in WRMs
    #: like the real device-health latch, and every groupby on this worker
    #: raises the transient DeviceBusyError so the controller fails the shard
    #: over to a replica holder.  Class-level default so partially
    #: constructed workers (tests build bare instances via ``__new__``) still
    #: answer ``prepare_wrm`` without the latch.
    _chaos_wedged = False

    def __init__(
        self,
        coordination_url=None,
        redis_url=None,
        data_dir=None,
        loglevel=None,
        restart_check=True,
        heartbeat_interval=DEFAULT_HEARTBEAT_INTERVAL,
        poll_timeout=DEFAULT_POLL_TIMEOUT,
        memory_limit_mb=DEFAULT_MEMORY_LIMIT_MB,
        gc_interval=DEFAULT_GC_INTERVAL,
    ):
        import logging

        bqueryd_tpu.configure_logging(loglevel or logging.INFO)
        self.worker_id = os.urandom(8).hex()
        self.logger = bqueryd_tpu.logger.getChild(
            f"{self.workertype}.{self.worker_id[:6]}"
        )
        self.node_name = socket_mod.gethostname()
        # fault injection (bqueryd_tpu.chaos): armed only when
        # BQUERYD_TPU_FAULT_PLAN is set; unarmed sites are one None check.
        # The store is wrapped so the coordination.store site can partition
        # THIS worker from Redis while its zmq sockets stay up.
        chaos.maybe_arm_from_env()
        self.store = chaos_store(
            coordination_store(
                coordination_url or redis_url
                or bqueryd_tpu.DEFAULT_COORDINATION_URL
            ),
            node_id=self.worker_id,
        )
        self.data_dir = data_dir or bqueryd_tpu.DEFAULT_DATA_DIR
        if self.workertype == "calc" and not os.path.isdir(self.data_dir):
            raise ValueError(f"Datadir {self.data_dir} is not a valid directory")
        self.restart_check = restart_check
        self.heartbeat_interval = heartbeat_interval
        self.poll_timeout = poll_timeout
        self.memory_limit_mb = memory_limit_mb
        self.gc_interval = gc_interval
        self._last_gc = time.time()

        # -- observability ---------------------------------------------------
        from bqueryd_tpu import obs
        from bqueryd_tpu.obs import http as obs_http

        self.metrics = obs.MetricsRegistry()
        self.metrics.gauge(
            "bqueryd_tpu_worker_rss_bytes",
            "resident set size of this worker process",
            fn=self._rss_bytes,
        )
        self.metrics.gauge(
            "bqueryd_tpu_worker_uptime_seconds",
            "seconds since this worker process started",
            fn=lambda: time.time() - self.start_time,
        )
        self.work_errors = self.metrics.counter(
            "bqueryd_tpu_worker_errors_total",
            "work items that raised (returned as ErrorMessage)",
        )
        # flight recorder: the always-on forensic ring (envelopes, state
        # transitions, errors, wedge latches) behind rpc.debug_bundle() —
        # its tail rides WRMs so the controller can assemble a cross-node
        # artifact even after this worker dies
        self.flight = obs.FlightRecorder(node_id=self.worker_id)
        self.metrics.gauge(
            "bqueryd_tpu_flight_evictions",
            "flight-ring events evicted by the entry/byte bounds (monotonic)",
            fn=lambda: self.flight.evictions,
        )
        self.metrics.gauge(
            "bqueryd_tpu_fault_injected_total",
            "faults injected by the armed chaos plan, process-lifetime "
            "(0 while BQUERYD_TPU_FAULT_PLAN is unarmed)",
            fn=chaos.injected_total,
        )
        self._wedge_gen_seen = devicehealth.health_snapshot()[
            "wedge_generation"
        ]
        self._metrics_server = obs_http.maybe_start(self.metrics, self.logger)

        self.context = zmq.Context.instance()
        self.socket = self.context.socket(zmq.ROUTER)
        self.socket.identity = self.worker_id.encode()
        self.socket.setsockopt(zmq.LINGER, 500)
        self.poller = zmq.Poller()
        self.poller.register(self.socket, zmq.POLLIN)

        self.controllers = set()     # connected controller addresses
        self.data_files = []
        self.running = False
        self.start_time = time.time()
        self._loop_started = self.start_time  # reset in go(), after warmup
        self.msg_count = 0
        self.last_heartbeat = 0.0
        self._hb_thread = None
        self._hb_stop = threading.Event()
        self._loop_thread = None

    # -- lifecycle ---------------------------------------------------------
    def go(self):
        self.running = True
        self._loop_thread = threading.current_thread()
        try:
            signal.signal(signal.SIGTERM, self._term_signal)
            if hasattr(signal, "SIGUSR1"):
                # local forensic dump: kill -USR1 <pid> writes this node's
                # debug snapshot (flight ring + compile registry + device
                # health) as one JSON file without needing a live controller
                signal.signal(signal.SIGUSR1, self._dump_debug_signal)
        except ValueError:
            pass  # not the main thread (in-process test clusters)
        self.logger.info("starting %s worker %s", self.workertype, self.worker_id)
        self._loop_started = time.time()  # fast-start anchor (post-warmup)
        self._start_heartbeat_thread()
        while self.running:
            try:
                self.heartbeat()
                events = dict(self.poller.poll(int(self.poll_timeout * 1000)))
                if self.socket in events:
                    self.handle_in()
            except zmq.ZMQError:
                self.logger.exception("zmq error in worker loop")
                time.sleep(0.2)
            except Exception:
                self.logger.exception("error in worker loop")
        self.stop()

    def _term_signal(self, *args):
        self.logger.info("SIGTERM received, stopping")
        self.running = False

    def _request_stop_only(self):
        """Flag the loop to exit.  Returns True when the caller is NOT the
        loop thread while the loop is alive — zmq sockets are
        single-thread-only, so socket teardown must then be left to the
        loop thread's own exit path (go()'s trailing stop())."""
        self.running = False
        self._hb_stop.set()
        loop = self._loop_thread
        external = (
            loop is not None
            and loop.is_alive()
            and threading.current_thread() is not loop
        )
        if not external and self._hb_thread is not None and (
            self._hb_thread.ident is not None  # racing go(): not yet started
        ):
            self._hb_thread.join(timeout=2.0)
        return external

    @staticmethod
    def _rss_bytes():
        import psutil

        return psutil.Process(os.getpid()).memory_info().rss

    def stop(self):
        # doubles as a cross-thread shutdown REQUEST (tests, embedders):
        # the flag ends the loop and the loop thread re-enters here for the
        # actual socket teardown
        if self._request_stop_only():
            return
        if self._metrics_server is not None:
            self._metrics_server.close()
            self._metrics_server = None
        for addr in list(self.controllers):
            try:
                self.send(addr, StopMessage({"worker_id": self.worker_id}))
            except zmq.ZMQError:
                pass
        if not self.socket.closed:
            self.socket.close()
            self.logger.info("worker %s stopped", self.worker_id)

    # -- liveness side-channel --------------------------------------------
    def _start_heartbeat_thread(self):
        """Broadcast WRMs from a dedicated thread so a long ``handle_work``
        (first-query XLA compile, a 10 M-row H2D, a slow blob fetch) cannot
        starve liveness and get this busy worker culled by the controller
        (the round-1 benchmark failure mode; cf. the reference's
        single-threaded WRM cycle, reference bqueryd/worker.py:131-143).

        ZeroMQ sockets are single-thread-only, so the thread owns a private
        DEALER socket per run; the controller keys worker liveness on the
        ``worker_id`` *inside* the WRM, not the delivering socket's identity,
        so heartbeats on this side channel refresh the same worker entry.
        """
        self._hb_stop.clear()
        self._hb_thread = threading.Thread(
            target=self._heartbeat_loop,
            name=f"hb-{self.worker_id[:6]}",
            daemon=True,
        )
        self._hb_thread.start()

    def _heartbeat_loop(self):
        # ONE DEALER per controller address: a DEALER with several connected
        # peers round-robins sends across their pipes, so per-controller
        # delivery each tick would be probabilistic (a dead peer's pipe
        # absorbs copies while another gets duplicates).  A socket that
        # connects to exactly one endpoint makes every tick's delivery
        # addressed, whatever the controller count.
        socks = {}  # controller address -> DEALER connected only to it
        try:
            while not self._hb_stop.is_set() and self.running:
                try:
                    current = self.store.smembers(bqueryd_tpu.REDIS_SET_KEY)
                    for addr in current - socks.keys():
                        sock = self.context.socket(zmq.DEALER)
                        # distinct identity: this socket must never be
                        # addressed as the worker
                        sock.identity = (self.worker_id + ".hb").encode()
                        sock.setsockopt(zmq.LINGER, 0)
                        try:
                            sock.connect(addr)
                        except zmq.ZMQError:
                            # one bad membership entry must not leak a socket
                            # per tick nor abort this tick's broadcast to the
                            # healthy controllers
                            sock.close()
                            continue
                        socks[addr] = sock
                    for addr in socks.keys() - current:
                        socks.pop(addr).close()
                    wrm = self.prepare_wrm()
                    wrm["liveness_only"] = True  # files rescanned on main loop
                    payload = wrm.to_json().encode()
                    for sock in socks.values():
                        try:
                            sock.send_multipart([payload], zmq.NOBLOCK)
                        except zmq.ZMQError:
                            pass
                except Exception:
                    self.logger.debug("heartbeat thread tick failed", exc_info=True)
                # re-broadcast well inside the controller's dead timeout
                self._hb_stop.wait(min(self.heartbeat_interval, 10.0))
        finally:
            for sock in socks.values():
                sock.close()

    # -- discovery / registration -----------------------------------------
    def _sync_controller_connections(self, sock, connected):
        """Reconcile the main ROUTER socket's connections with the membership
        set.  (The liveness thread manages its own per-controller DEALER
        sockets inline in ``_heartbeat_loop`` — one socket per address, so
        heartbeat delivery is addressed rather than round-robined.)"""
        current = self.store.smembers(bqueryd_tpu.REDIS_SET_KEY)
        for addr in current - connected:
            self.logger.debug("connecting to controller %s", addr)
            sock.connect(addr)
            connected.add(addr)
        for addr in connected - current:
            self.logger.debug("dropping dead controller %s", addr)
            try:
                sock.disconnect(addr)
            except zmq.ZMQError:
                pass
            connected.discard(addr)
        return connected

    def check_controllers(self):
        self._sync_controller_connections(self.socket, self.controllers)

    def check_datafiles(self):
        found = []
        if os.path.isdir(self.data_dir):
            for name in sorted(os.listdir(self.data_dir)):
                if name.endswith(SHARD_EXTENSIONS) and os.path.isdir(
                    os.path.join(self.data_dir, name)
                ):
                    found.append(name)
        self.data_files = found
        return found

    def shard_stats(self):
        """Per-shard planning statistics advertised in the WRM (rows, column
        min/max, key cardinalities); None for roles without tables.  The calc
        role overrides."""
        return None

    #: re-advertise unchanged shard stats at most this often: WRMs fire every
    #: heartbeat on two threads, and serializing O(shards x columns) stats
    #: into each would make liveness cost scale with data size.  The
    #: periodic re-send (rather than change-only) covers controller restarts,
    #: which silently lose absorbed stats.
    STATS_READVERTISE_S = 60.0

    def _stats_to_advertise(self):
        """Shard stats for this WRM, or None when the receiver already has
        them (same snapshot object advertised within the re-send window)."""
        stats = self.shard_stats()
        if stats is None:
            return None
        now = time.time()
        if (
            stats is getattr(self, "_stats_sent_obj", None)
            and now - getattr(self, "_stats_sent_ts", 0.0)
            < self.STATS_READVERTISE_S
        ):
            return None
        self._stats_sent_obj = stats
        self._stats_sent_ts = now
        return stats

    def _backend_wedged(self):
        """The device-health latch this worker advertises.  CALC workers own
        the device, so their heartbeat ticks the probe clock too — an IDLE
        wedged worker still recovers (and stops advertising wedged) without
        waiting for a query.  Downloader/move roles never touch the device;
        their reads stay passive so a WRM can never spawn a jax probe thread
        as a side effect.  Instance-overridable (tests wedge ONE worker of an
        in-process cluster without touching the process-global latch).
        A chaos ``wedge`` fault latches the same advertisement path."""
        if self._chaos_wedged:
            return True
        return devicehealth.backend_wedged(launch=self.workertype == "calc")

    def _debug_snapshot(self, flight_limit=32):
        """This node's slice of a debug bundle: flight-ring tail, compile
        registry, device health, runtime versions.  Rides every WRM (small:
        the tail is capped) so a controller can produce a cross-node
        artifact even for a worker that has since died."""
        from bqueryd_tpu.obs import profile

        flight = getattr(self, "flight", None)
        # NOTE: no histogram snapshot here — the WRM's own "metrics" key
        # already carries it, and the controller keeps the latest copy per
        # worker; duplicating it would double every heartbeat's size
        return {
            "node_id": getattr(self, "worker_id", None),
            "workertype": self.workertype,
            "pid": os.getpid(),
            "flight": flight.tail(flight_limit) if flight is not None else [],
            "flight_evictions": (
                flight.evictions if flight is not None else 0
            ),
            "compile": profile.profiler().snapshot(),
            "device_health": devicehealth.health_snapshot(),
            "runtime": profile.runtime_versions(),
            "compile_cache": profile.compile_cache_info(),
        }

    #: re-send an unchanged debug slice at most this often (covers
    #: controller restarts, which silently lose absorbed slices) — same
    #: policy as STATS_READVERTISE_S for shard stats
    DEBUG_READVERTISE_S = 60.0

    def _debug_change_key(self):
        """Cheap fingerprint of the debug slice's inputs: flight ring seq,
        profiler call seq + cache counters, wedge generation."""
        from bqueryd_tpu.obs import profile

        flight = getattr(self, "flight", None)
        prof = profile.profiler()
        return (
            flight._seq if flight is not None else 0,
            prof._call_seq,
            prof.jit_cache_hits,
            prof.persistent_cache_hits,
            devicehealth.health_snapshot()["wedge_generation"],
        )

    def _debug_to_advertise(self):
        """The debug slice for this WRM, or None when the receiver already
        has it (unchanged since the last send, inside the re-send window).
        WRMs fire every <=10 s on two threads; serializing an identical
        multi-KB snapshot into each would tax every heartbeat for data that
        changes only on compile/flight/wedge events."""
        key = self._debug_change_key()
        now = time.time()
        if (
            key == getattr(self, "_debug_sent_key", None)
            and now - getattr(self, "_debug_sent_ts", 0.0)
            < self.DEBUG_READVERTISE_S
        ):
            return None
        snapshot = self._debug_snapshot()
        self._debug_sent_key = key
        self._debug_sent_ts = now
        return snapshot

    def _dump_debug_signal(self, *args):
        from bqueryd_tpu.obs import flightrec, profile

        try:
            # build_bundle applies the same path redaction the controller's
            # bundle gets — a worker-side dump must be just as safe to
            # attach to a public bug report
            allowed = [self.data_dir]
            cache_path = profile.compile_cache_info().get("path")
            if cache_path:
                allowed.append(cache_path)
            path = flightrec.dump_bundle(
                flightrec.build_bundle(
                    None,
                    {self.worker_id: {
                        "data": self._debug_snapshot(flight_limit=512),
                        "ts": time.time(),
                        "registered": True,
                    }},
                    allowed_path_prefixes=allowed,
                ),
                role=self.workertype,
            )
            self.logger.warning("SIGUSR1: debug snapshot written to %s", path)
        except Exception:
            self.logger.exception("SIGUSR1 debug dump failed")

    def _calibration_to_advertise(self):
        """The WRM calibration summary, or None (non-calc role, disabled,
        or cold) — a calibration failure must never break liveness."""
        if getattr(self, "workertype", None) != "calc":
            return None
        try:
            from bqueryd_tpu.plan import calibrate

            return calibrate.summary_for_wire()
        except Exception:
            return None

    def _pipeline_busy_to_advertise(self):
        """The StageClock busy snapshot riding calc WRMs: the controller's
        capacity model (obs.capacity) reads per-stage busy DELTAS from it
        to name each worker's bottleneck stage (decode vs kernel vs merge)
        beside its utilization.  Cumulative totals — the absorb side
        rebases on a restart's reset, same contract as the histogram
        snapshot.  None for non-calc roles (no data path, no stages) and
        on any failure: busy accounting must never break liveness."""
        if getattr(self, "workertype", None) != "calc":
            return None
        try:
            from bqueryd_tpu.parallel import pipeline

            return pipeline.clock().snapshot()
        except Exception:
            return None

    def prepare_wrm(self):
        # getattr defence: embedders and tests build workers piecemeal
        # (__new__), and a missing registry must never break the WRM
        # heartbeat (same rule as shard_stats)
        registry = getattr(self, "metrics", None)
        errors = getattr(self, "work_errors", None)
        try:
            debug = self._debug_to_advertise()
        except Exception:
            debug = None  # a debug failure must never break liveness
        return WorkerRegisterMessage(
            {
                "worker_id": self.worker_id,
                "node": self.node_name,
                "ip": get_my_ip(),
                "data_dir": self.data_dir,
                "data_files": self.data_files,
                "workertype": self.workertype,
                "pid": os.getpid(),
                "uptime": time.time() - self.start_time,
                "msg_count": self.msg_count,
                # degraded-mode visibility: operators watching rpc.info()
                # see a wedged accelerator the moment routing does (and the
                # controller's health scorer marks this worker "wedged")
                "backend_wedged": self._backend_wedged(),
                # error-counter total: the health scorer's windowed error
                # rate is the delta of this across heartbeats
                "work_errors": errors.value if errors is not None else 0,
                # the node's debug-bundle slice (flight tail + compile
                # registry + device health), absorbed controller-side for
                # rpc.debug_bundle()
                "debug": debug,
                # metadata-only per-shard stats (rows, min/max, cardinality)
                # feeding the controller's plan-time pruning and kernel-
                # strategy selection; None for non-calc roles and for beats
                # where the unchanged stats were advertised recently
                "shard_stats": self._stats_to_advertise(),
                # measured-cost calibration summary (plan.calibrate): the
                # worker's per-(rows, groups, dtype, backend, strategy)
                # kernel-wall cells, absorbed controller-side into the
                # model select_calibrated consults; None when calibration
                # is disabled or nothing has been measured yet
                "calibration": self._calibration_to_advertise(),
                # latency histogram snapshot (fixed buckets, JSON-safe):
                # controllers aggregate these fleet-wide by bucket-vector
                # addition (get_info "worker_histograms" + peer gossip)
                "metrics": (
                    registry.histogram_snapshot()
                    if registry is not None else None
                ),
                # per-stage pipeline busy clocks (cumulative seconds): the
                # capacity model's bottleneck-stage signal; None for
                # non-calc roles
                "pipeline_busy": self._pipeline_busy_to_advertise(),
            }
        )

    def heartbeat(self):
        now = time.time()
        # wedge-latch transitions land in the flight ring the moment the
        # loop notices them (forensic event: never gated by the metrics
        # kill switch) — the debug bundle's answer to "when did it wedge?"
        health = devicehealth.health_snapshot()
        if health["wedge_generation"] != self._wedge_gen_seen:
            self._wedge_gen_seen = health["wedge_generation"]
            self.flight.record(
                "wedge_latched",
                generation=health["wedge_generation"],
                abandoned_probes=health["abandoned_probes"],
            )
            self.logger.warning(
                "accelerator backend latched wedged (generation %d)",
                health["wedge_generation"],
            )
        interval = self.heartbeat_interval
        # fast start: the first WRM on a freshly connected ROUTER socket is
        # dropped if the peer handshake hasn't finished (identity not yet
        # routable), so rebroadcast every second until registration settles
        # rather than waiting a full heartbeat_interval to become queryable
        if now - self._loop_started < 10.0:
            interval = min(interval, 1.0)
        if now - self.last_heartbeat < interval:
            return
        self.last_heartbeat = now
        self.check_controllers()
        self.check_datafiles()
        self.send_to_all(self.prepare_wrm())

    # -- messaging ---------------------------------------------------------
    def send(self, addr, msg):
        """Send to a controller by identity; a bytes 'data' value travels as
        its own frame so JSON never sees binary."""
        data = msg.pop("data", None)
        frames = [
            addr.encode() if isinstance(addr, str) else addr,
            msg.to_json().encode(),
        ]
        if data is not None:
            if isinstance(data, str):
                data = data.encode()
            frames.append(data)
        self.socket.send_multipart(frames)

    def send_to_all(self, msg):
        for addr in list(self.controllers):
            try:
                self.send(addr, msg.copy())
            except zmq.ZMQError as exc:
                self.logger.debug("send to %s failed: %s", addr, exc)

    def handle_in(self):
        frames = self.socket.recv_multipart()
        if len(frames) < 2:
            self.logger.warning("dropping short message: %r", frames)
            return
        sender, payload = frames[0], frames[1]
        self.msg_count += 1
        try:
            msg = msg_factory(payload)
        except messages.MalformedMessage:
            self.logger.warning("dropping malformed message from %r", sender)
            return
        if msg.isa(StopMessage) or msg.isa("kill"):
            self.running = False
            return
        if msg.isa("loglevel"):
            self._set_loglevel(msg)
            return
        if msg.isa("info"):
            self.send(sender, self.prepare_wrm())
            return
        self.handle(msg, sender)

    def _set_loglevel(self, msg):
        import logging

        args, _ = msg.get_args_kwargs()
        level = {"debug": logging.DEBUG, "info": logging.INFO}.get(
            (args[0] if args else "info"), logging.INFO
        )
        bqueryd_tpu.logger.setLevel(level)
        self.logger.info("loglevel set to %s", level)

    # -- work --------------------------------------------------------------
    def handle(self, msg, sender):
        from bqueryd_tpu import obs

        busy = BusyMessage({"worker_id": self.worker_id})
        self.send_to_all(busy)
        wire = msg.get_trace()
        log_fields = {
            "trace_id": (wire or {}).get("trace_id"),
            "query_id": msg.get("parent_token") or msg.get("token"),
        }
        # flight ring: every envelope this worker accepts (hot path — obeys
        # the metrics kill switch; failures below are recorded regardless)
        if obs.enabled():
            self.flight.record(
                "envelope",
                verb=msg.get("payload"),
                token=msg.get("token"),
                parent=msg.get("parent_token"),
                trace_id=log_fields["trace_id"],
            )
        work_clock = time.perf_counter()
        # correlation ids on every log line this work emits (JSON
        # formatter), and the active TraceContext for trace_span tagging;
        # the except body stays INSIDE the bind — the failure traceback is
        # the log line that most needs to join the rpc.trace() waterfall
        with obs.bind_log_context(**log_fields), obs.use_trace(
            obs.TraceContext.from_wire(wire)
        ):
            try:
                # chaos site worker.execute: transient raises (the failover
                # trigger), wedge latch, die-after-ack (the Busy above WAS
                # the ack), delay — all before the deadline check so an
                # injected stall can expire a deadline like a real one
                fault = chaos.fire(
                    "worker.execute",
                    worker=self.worker_id,
                    verb=msg.get("payload"),
                    token=msg.get("token"),
                    filename=str(msg.get("filename")),
                ) if chaos.enabled() else None
                if fault is not None and fault.action == "die_after_ack":
                    self._chaos_die()
                    return  # hard crash: no reply, no Done, no goodbye
                if fault is not None and fault.action == "wedge":
                    self._chaos_wedged = True
                    self.flight.record("chaos_wedged")
                    self.logger.warning(
                        "chaos: wedge latched — advertising backend_wedged"
                    )
                if self._chaos_wedged and msg.isa("groupby"):
                    raise chaos.DeviceBusyError(
                        "chaos: accelerator backend wedged"
                    )
                if msg.deadline_expired():
                    # the client's budget is already gone: burning kernel
                    # time on an answer nobody is waiting for starves
                    # admitted queries
                    raise TimeoutError(
                        f"deadline exceeded "
                        f"{-msg.deadline_remaining():.3f}s before execution"
                    )
                result = self.handle_work(msg)
            except Exception as exc:
                self.logger.exception("error handling work")
                self.work_errors.inc()
                # forensic event (never gated): the first line of the
                # failure plus its correlation ids — the flight ring is what
                # explains an ErrorMessage after the query is long gone
                self.flight.record(
                    "work_error",
                    verb=msg.get("payload"),
                    token=msg.get("token"),
                    trace_id=log_fields["trace_id"],
                    error=f"{type(exc).__name__}: {exc}"[:300],
                )
                err = ErrorMessage(msg)
                err["payload"] = traceback.format_exc()
                if isinstance(exc, chaos.TransientError):
                    # retryable class (DeviceBusyError & co): the controller
                    # fails the shard over to a different holder instead of
                    # aborting the parent query (messages.py `transient`)
                    err["transient"] = True
                result = err
            else:
                if obs.enabled():
                    self.flight.record(
                        "work_done",
                        verb=msg.get("payload"),
                        token=msg.get("token"),
                        trace_id=log_fields["trace_id"],
                        wall_s=round(time.perf_counter() - work_clock, 6),
                    )
        if result is not None:
            # chaos site worker.reply: drop loses the finished result on
            # the wire (dispatch timeout + failover must recover), delay
            # stretches reply latency (hedging territory)
            fault = chaos.fire(
                "worker.reply",
                worker=self.worker_id,
                verb=msg.get("payload"),
                token=msg.get("token"),
            ) if chaos.enabled() else None
            if fault is not None and fault.action == "drop":
                self.flight.record(
                    "chaos_reply_dropped", token=msg.get("token")
                )
                result = None
        if result is not None:
            try:
                self.send(sender, result)
            except zmq.ZMQError:
                self.logger.exception("could not send result to %r", sender)
        self.send_to_all(DoneMessage({"worker_id": self.worker_id}))
        # The reference collects after EVERY task (reference
        # bqueryd/worker.py:226) — necessary for its per-query bcolz
        # allocations, but here steady-state serving is cache-resident and a
        # full gen-2 collect walks those caches: ~17 ms per query at 10 M
        # rows, a measured ~20% of the fixed per-query cost.  Throttle to
        # one collect per interval; the RSS watchdog (_check_mem) remains
        # the backstop between collects.
        now = time.time()
        if now - self._last_gc >= self.gc_interval:
            self._last_gc = now
            gc.collect()
        self._check_mem()

    def _chaos_die(self):
        """die_after_ack: simulate a hard crash after accepting work — the
        Busy ack went out, then silence.  No reply, no Done, no StopMessage
        goodbye, heartbeats stop; the controller must recover through its
        dispatch timeout / dead-worker cull + replica failover.  The loop
        thread still runs its own socket teardown on exit (zmq sockets are
        single-thread-only)."""
        self.logger.warning(
            "chaos: die_after_ack fired — simulating hard worker crash"
        )
        self.flight.record("chaos_die_after_ack")
        self._hb_stop.set()
        self.send = lambda *a, **k: None  # silent: no replies, no goodbye
        self.running = False

    def handle_work(self, msg):
        # base verbs shared by every role
        if msg.isa("readfile"):
            return self._readfile(msg)
        if msg.isa("sleep"):
            args, _ = msg.get_args_kwargs()
            duration = float(args[0]) if args else 0.0
            time.sleep(min(duration, 60.0))
            reply = msg.copy()
            reply.add_as_binary("result", f"slept {duration} {self.worker_id}")
            return reply
        raise ValueError(f"unhandled message payload {msg.get('payload')!r}")

    def _readfile(self, msg):
        """Read a file strictly inside data_dir (the reference's readfile verb,
        reference bqueryd/worker.py:216-220, with path traversal closed)."""
        args, _ = msg.get_args_kwargs()
        filename = args[0]
        path = os.path.realpath(os.path.join(self.data_dir, filename))
        if not path.startswith(os.path.realpath(self.data_dir) + os.sep):
            raise ValueError(f"path {filename!r} escapes data_dir")
        with open(path, "rb") as f:
            reply = msg.copy()
            reply["data"] = f.read()
            return reply

    def _check_mem(self):
        if not self.restart_check:
            return
        try:
            import psutil

            rss_mb = psutil.Process(os.getpid()).memory_info().rss / 1e6
        except Exception:
            return
        if rss_mb > self.memory_limit_mb:
            # shed caches first; suicide (the reference's policy, reference
            # bqueryd/worker.py:232-241) only if that wasn't enough
            shed_mb = self._shed_caches()
            if shed_mb is not None and shed_mb <= self.memory_limit_mb:
                return
            # unmeasurable post-shed RSS counts as still-over: the pre-shed
            # reading already proved the limit breached, and a silent pass
            # here would disable the supervisor-restart safety net
            self.logger.warning(
                "RSS %s MB above limit %d MB, stopping for supervisor restart",
                "?" if shed_mb is None else f"{shed_mb:.0f}",
                self.memory_limit_mb,
            )
            self.running = False

    def _shed_caches(self):
        """Drop query caches + collect; returns post-shed RSS in MB."""
        import gc

        try:
            from bqueryd_tpu.storage import free_cachemem

            free_cachemem()
        except Exception:
            pass
        executor = getattr(self, "_mesh_executor", None)
        if executor is not None:
            executor.clear_caches()
        engine = getattr(self, "_engine", None)
        if engine is not None:
            engine.clear_caches()
        # dict-column instances pin their value dictionaries — not "light"
        # under memory pressure
        getattr(self, "_table_cache", {}).clear()
        result_cache = getattr(self, "_result_cache", None)
        if result_cache:
            result_cache.clear()
        delta_cache = getattr(self, "_delta_cache", None)
        if delta_cache is not None:
            delta_cache.clear()
        gc.collect()
        try:
            import psutil

            return psutil.Process(os.getpid()).memory_info().rss / 1e6
        except Exception:
            return None


class WorkerNode(WorkerBase):
    """The compute leaf: executes groupby / execute_code (reference
    bqueryd/worker.py:247-348)."""

    workertype = "calc"

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self._engine = None
        self._mesh_executor = None
        self._result_cache = None
        self._table_cache = {}
        self._stats_collector = None
        self._warmup_thread = None
        # device-health gauges: read-only snapshots (never launch a probe
        # from a metrics scrape) — operators see the wedge latch and its
        # probe debt wherever they already scrape worker metrics
        snap = devicehealth.health_snapshot
        self.metrics.gauge(
            "bqueryd_tpu_backend_wedged",
            "1 while the accelerator backend is latched as wedged",
            fn=lambda: snap()["wedged"],
        )
        self.metrics.gauge(
            "bqueryd_tpu_device_probes_abandoned",
            "health probes written off as hung since the last success",
            fn=lambda: snap()["abandoned_probes"],
        )
        self.groupby_queries = self.metrics.counter(
            "bqueryd_tpu_worker_groupby_total",
            "groupby CalcMessages executed by this worker",
        )
        # -- streaming ingest (PR 14) ------------------------------------
        self._delta_cache = None  # DeltaAggCache, built lazily when enabled
        self._last_chunk_prune = None
        self.appends_total = self.metrics.counter(
            "bqueryd_tpu_worker_appends_total",
            "append CalcMessages applied by this worker",
        )
        self.append_rows_total = self.metrics.counter(
            "bqueryd_tpu_worker_append_rows_total",
            "rows appended into served shards by this worker",
        )
        self.chunks_decoded_total = self.metrics.counter(
            "bqueryd_tpu_chunks_decoded_total",
            "storage chunks the zone-map pruning pass kept for decode on "
            "filtered queries (with chunks_skipped: the decode fraction)",
        )
        self.chunks_skipped_total = self.metrics.counter(
            "bqueryd_tpu_chunks_skipped_total",
            "storage chunks proven unmatchable by per-chunk zone maps and "
            "never decoded",
        )
        self.delta_refreshes_total = self.metrics.counter(
            "bqueryd_tpu_delta_refreshes_total",
            "cached aggregate results refreshed by aggregating only "
            "appended chunks and merging the delta partial "
            "(ops.workingset.DeltaAggCache)",
        )
        self.metrics.gauge(
            "bqueryd_tpu_delta_cache_bytes",
            "serialized payload bytes held by the delta-maintained "
            "aggregate cache",
            fn=lambda: (
                0 if self._delta_cache is None
                else self._delta_cache.nbytes
            ),
        )
        self.groupby_seconds = self.metrics.histogram(
            "bqueryd_tpu_worker_groupby_seconds",
            "whole-CalcMessage wall on the worker (open to serialize)",
        )
        from bqueryd_tpu.obs.metrics import BYTES_BUCKETS

        self.reply_bytes = self.metrics.histogram(
            "bqueryd_tpu_reply_bytes",
            "serialized groupby result-payload size per calc reply "
            "(the wire bytes the device-resident merge shrinks)",
            buckets=BYTES_BUCKETS,
        )
        # the process-global compile/device profiler exposed on this node's
        # registry: compile-seconds histogram (same instance process-wide),
        # jit/persistent-cache counters, HBM watermark gauges
        from bqueryd_tpu.obs import profile as obs_profile

        obs_profile.profiler().bind(self.metrics)
        self._bind_pipeline_metrics()
        # join a multi-host JAX job if configured (pod slice = one logical
        # calc worker; must happen before any JAX backend touch)
        from bqueryd_tpu import ops

        ops.maybe_init_distributed(self.logger)

    def _bind_pipeline_metrics(self):
        """Pipeline + working-set telemetry on this node's registry: stage
        busy clocks (process-global — the worker owns the process's data
        path), working-set segment counters (per mesh executor, created
        lazily: gauges read 0 until the first mesh query), and result-cache
        counters.  All fn-backed so a scrape reads live state."""
        from bqueryd_tpu.parallel import pipeline

        self.metrics.gauge(
            "bqueryd_tpu_pipeline_threads",
            "effective shard-pipeline pool width "
            "(BQUERYD_TPU_PIPELINE_THREADS)",
            fn=pipeline.pipeline_threads,
        )
        for stage_name in pipeline.STAGES:
            self.metrics.gauge(
                "bqueryd_tpu_pipeline_busy_seconds",
                "cumulative wall spent inside each pipeline stage across "
                "all threads (sum > query wall proves stage overlap)",
                labels={"stage": stage_name},
                fn=(lambda s=stage_name: pipeline.clock().busy_seconds(s)),
            )

        def ws_stat(segment, field):
            executor = self._mesh_executor
            if executor is None:
                return 0
            # direct attribute reads (plain ints under the GIL): a /metrics
            # scrape must not rebuild full stats() snapshots — 12 gauges per
            # scrape would take every cache lock 4x each against the hot path
            cache = executor.workingset.segment(segment)
            return cache.nbytes if field == "bytes" else getattr(cache, field)

        for segment in ("align", "codes", "blocks"):
            for field, help_text in (
                ("bytes", "bytes held per working-set cache segment"),
                ("hits", "working-set cache hits per segment (monotonic)"),
                ("misses",
                 "working-set cache misses per segment (monotonic)"),
                ("evictions",
                 "working-set LRU evictions per segment (monotonic)"),
            ):
                self.metrics.gauge(
                    f"bqueryd_tpu_workingset_{field}",
                    help_text,
                    labels={"segment": segment},
                    fn=(
                        lambda s=segment, f=field: ws_stat(s, f)
                    ),
                )
        self.metrics.gauge(
            "bqueryd_tpu_workingset_pressure_evictions",
            "device cache entries shed by the HBM watermark policy "
            "(monotonic)",
            fn=lambda: (
                0 if self._mesh_executor is None
                else self._mesh_executor.workingset.pressure_evictions
            ),
        )

        # device-resident merge byte movement (parallel/devicemerge): D2H
        # bytes per merge mode and the per-device partial bytes the
        # span-owned collective merge kept out of the fetch.  Process-global
        # like the stage clocks — the worker owns the process's data path.
        from bqueryd_tpu.parallel import devicemerge

        for mode in ("device", "host"):
            self.metrics.gauge(
                "bqueryd_tpu_merge_bytes_fetched",
                "D2H bytes fetched by the partial-table merge, per mode "
                "(device = final spans only; host = every device's table)",
                labels={"mode": mode},
                fn=(lambda m=mode: devicemerge.stats().fetched(m)),
            )
            self.metrics.gauge(
                "bqueryd_tpu_merge_queries",
                "mesh queries merged per merge mode (monotonic)",
                labels={"mode": mode},
                fn=(lambda m=mode: devicemerge.stats().count(m)),
            )
        self.metrics.gauge(
            "bqueryd_tpu_merge_d2h_bytes_saved",
            "per-device partial-table bytes the device-resident merge kept "
            "out of the D2H fetch (monotonic)",
            fn=lambda: devicemerge.stats().saved(),
        )

        def result_stat(field):
            cache = self._result_cache
            if cache is None or cache is False:  # unbuilt or disabled
                return 0
            return getattr(cache, field)

        for field, help_text in (
            ("hits", "worker result-cache hits (monotonic)"),
            ("misses", "worker result-cache misses (monotonic)"),
            ("evictions", "worker result-cache LRU evictions (monotonic)"),
        ):
            self.metrics.gauge(
                f"bqueryd_tpu_result_cache_{field}",
                help_text,
                fn=(lambda f=field: result_stat(f)),
            )

    def go(self):
        if os.environ.get("BQUERYD_TPU_WARMUP", "1") == "1":
            self._warmup_thread = threading.Thread(
                target=self.warmup,
                name=f"warmup-{self.worker_id[:6]}",
                daemon=True,
            )
            self._warmup_thread.start()
        super().go()

    def warmup(self):
        """Prime the JAX backend (PJRT client init + a tiny kernel compile)
        in the BACKGROUND so the worker advertises its shards immediately.

        Backend bring-up on a tunneled TPU can take many minutes; gating the
        first WRM broadcast on it made every worker restart a registration
        blackout (the round-2 benchmark failure).  Instead the worker is
        queryable at once — a query arriving mid-warmup simply blocks on the
        same JAX backend-init lock, and the liveness heartbeat thread plus
        the controller's inflight-aware cull keep the busy worker alive for
        however long that takes (reference bqueryd/worker.py:107-143 was
        queryable ~20s after start; this restores that property on TPU)."""
        t0 = time.time()
        self.logger.info("starting JAX backend warmup in background")
        try:
            import numpy as np

            from bqueryd_tpu import ops

            codes = np.zeros(8, dtype=np.int32)
            vals = np.ones(8, dtype=np.int64)
            partials = ops.partial_tables(codes, (vals,), ("sum",), 4, None)
            ops.finalize(partials, ("sum",))
            # a dispatch-floor sample taken by a query while this compile
            # held the backend is inflated; replace it with a clean one so
            # host routing doesn't mis-route for the process lifetime
            from bqueryd_tpu.models.query import device_dispatch_floor

            device_dispatch_floor(remeasure=True)
            self.logger.info("kernel warmup done in %.1fs", time.time() - t0)
        except Exception:
            self.logger.exception("kernel warmup failed (continuing)")

    def shard_stats(self):
        """Metadata-only stats for every advertised shard (memoized; see
        plan.stats.StatsCollector).  Disable with BQUERYD_TPU_SHARD_STATS=0
        — the planner then treats this worker's shards as stats-less (no
        pruning, auto strategy)."""
        if os.environ.get("BQUERYD_TPU_SHARD_STATS", "1") == "0":
            return None
        # getattr defences: embedders (and tests) build workers piecemeal,
        # and a stats failure must never break the WRM heartbeat
        try:
            collector = getattr(self, "_stats_collector", None)
            if collector is None:
                from bqueryd_tpu.plan.stats import StatsCollector

                collector = StatsCollector(table_opener=self._open_table)
                self._stats_collector = collector
            return collector.collect(
                self.data_dir, list(self.data_files)
            )
        except Exception:
            log = getattr(self, "logger", None)
            if log is not None:
                log.debug("shard stats gathering failed", exc_info=True)
            return None

    @property
    def engine(self):
        if self._engine is None:
            from bqueryd_tpu.models.query import QueryEngine

            self._engine = QueryEngine()
        return self._engine

    @property
    def mesh_executor(self):
        if self._mesh_executor is None:
            from bqueryd_tpu.parallel.executor import MeshQueryExecutor

            self._mesh_executor = MeshQueryExecutor()
        return self._mesh_executor

    @property
    def result_cache(self):
        """Serialized-result cache keyed by (table identities, query
        signature).  Table identity includes the shard's meta.json mtime, so
        activation of new data invalidates naturally — a repeated query on
        unchanged shards costs one dict lookup, no kernel dispatch.  Bounded
        by BQUERYD_TPU_RESULT_CACHE_BYTES (0 disables)."""
        if self._result_cache is None:
            from bqueryd_tpu.utils.cache import BytesCappedCache

            try:
                cap = int(
                    os.environ.get(
                        "BQUERYD_TPU_RESULT_CACHE_BYTES", 256 * 1024**2
                    )
                )
            except ValueError:
                self.logger.warning(
                    "unparseable BQUERYD_TPU_RESULT_CACHE_BYTES, cache off"
                )
                cap = 0
            self._result_cache = BytesCappedCache(cap) if cap > 0 else False
        # explicit False check: an EMPTY BytesCappedCache is len()-falsy
        return None if self._result_cache is False else self._result_cache

    # -- delta-maintained hot aggregates (streaming ingest, PR 14) ---------
    def delta_cache(self):
        """The per-worker :class:`~bqueryd_tpu.ops.workingset.DeltaAggCache`
        (None while BQUERYD_TPU_DELTA_SERVE=0)."""
        from bqueryd_tpu.ops import workingset

        if not workingset.delta_serve_enabled():
            return None
        if self._delta_cache is None:
            self._delta_cache = workingset.DeltaAggCache()
        return self._delta_cache

    @staticmethod
    def _delta_eligible(query):
        """Shapes whose cached result can be maintained by merging a
        tail-only partial: plain mergeable aggregations.  Basket expansion
        re-selects OLD rows when a NEW row of the same basket matches, so
        its cached result is not tail-refreshable; distinct counts carry
        value sets the flat merge forms don't cover here."""
        from bqueryd_tpu import ops

        return (
            query is not None
            and query.aggregate
            and not query.expand_filter_column
            and all(op in ops.MERGEABLE_OPS for op in query.ops)
        )

    def _delta_key(self, tables, query):
        return (
            tuple(os.path.realpath(t.rootdir) for t in tables),
            query.signature(),
        )

    def _serve_delta(self, cache, tables, query, timer):
        """Serve a grown shard group from the delta cache: aggregate ONLY
        the appended chunks of each grown table through the ordinary
        engine path and merge the tail partials into the cached payload.
        Returns the refreshed serialized payload, or None (no entry /
        not an append-only growth — the caller recomputes)."""
        from bqueryd_tpu.models.query import ResultPayload
        from bqueryd_tpu.parallel import hostmerge

        key = self._delta_key(tables, query)
        entry = cache.get(key)
        if entry is None:
            return None
        per_table_ids = cache.refresh_ids(entry, tables)
        if per_table_ids is None:
            # rewrite/reshard/shrink: the mtime-keyed identity backstop —
            # drop the entry, recompute fresh (and re-base below)
            cache.discard(key)
            return None
        tails = [
            table.chunk_view(ids)
            for table, ids in zip(tables, per_table_ids)
            if ids
        ]
        if not tails:
            # no growth: identical repeats are the RESULT cache's job —
            # serving the bytes here would turn the delta cache into a
            # second result cache that ignores RESULT_CACHE_BYTES=0
            return None
        payloads = [ResultPayload.from_bytes(entry["data"])]
        delta_rows = 0
        self.engine.timer = timer
        for view in tails:
            payloads.append(self.engine.execute_local(view, query))
            delta_rows += int(view.nrows)
        with timer.phase("hostmerge"):
            merged = ResultPayload(hostmerge.merge_payloads(payloads))
        with timer.phase("serialize"):
            data = merged.to_bytes()
        cache.store(key, tables, data)
        cache.refreshes += 1
        cache.delta_rows += delta_rows
        self.delta_refreshes_total.inc()
        self._last_merge_mode = "host"
        return data

    def _append_rows(self, msg):
        """The ``rpc.append`` verb: apply a dataframe-like batch of rows to
        a locally served shard.  Column data + chunk indexes commit before
        the meta.json row count (storage.ctable.append_dataframe), so
        concurrent queries on this worker keep a consistent snapshot; the
        stats collector window is dropped so the grown shard advertises
        fresh min/max/cardinality on the next heartbeat."""
        if os.environ.get("BQUERYD_TPU_APPEND", "1") == "0":
            raise ValueError(
                "streaming append disabled on this worker "
                "(BQUERYD_TPU_APPEND=0)"
            )
        from bqueryd_tpu.storage.ctable import ctable

        args, _kwargs = msg.get_args_kwargs()
        if len(args) != 2:
            raise ValueError("append needs (filename, dataframe_like)")
        filename, frame = args
        rootdir = os.path.realpath(os.path.join(self.data_dir, filename))
        if not rootdir.startswith(
            os.path.realpath(self.data_dir) + os.sep
        ):
            raise ValueError(f"path {filename!r} escapes data_dir")
        if not os.path.exists(os.path.join(rootdir, "meta.json")):
            raise ValueError(f"Path {rootdir} does not exist")
        table = ctable(rootdir, mode="a")
        appended = table.append(frame)
        self.appends_total.inc()
        self.append_rows_total.inc(appended)
        collector = self._stats_collector
        if collector is not None:
            collector.invalidate()
        self.flight.record(
            "append", filename=filename, rows=appended,
            total=int(table.nrows),
        )
        reply = msg.copy()
        # the request params carry the whole appended frame — echoing them
        # back worker->controller per holder would double the wire cost
        reply.pop("params", None)
        reply.add_as_binary(
            "result",
            {
                "filename": filename,
                "appended": int(appended),
                "rows": int(table.nrows),
                "worker": self.worker_id,
                "node": self.node_name,
            },
        )
        return reply

    def _rollup_census(self, table):
        """Column metadata the subsumption lattice proves against:
        per-column kind ("int" columns are null-free by dtype — that is
        what licenses key-folds), per-chunk zone maps (what licenses
        zone-proof filter subsumption).  Metadata-only — no chunk decode."""
        import numpy as np

        from bqueryd_tpu.storage.ctable import KIND_DATETIME, KIND_NUMERIC

        cols = {}
        for name in table.names:
            k = table.kind(name)
            if k == KIND_NUMERIC:
                np_kind = np.dtype(table.physical_dtype(name)).kind
                kind = "int" if np_kind in "iu" else "float"
            elif k == KIND_DATETIME:
                kind = "datetime"
            else:
                kind = "dict"
            zones = (
                table.chunk_zone_maps(name)
                if k in (KIND_NUMERIC, KIND_DATETIME) else None
            )
            cols[name] = {
                "kind": kind,
                "zones": zones,
                # float/datetime zone maps skip NaN/NaT rows, so null
                # absence is only ever provable for integer columns
                "nulls": kind != "int",
            }
        return cols

    def _rollup_build(self, msg):
        """The controller-originated ``rollup`` verb: materialize (or
        delta-refresh) the mergeable partials of one hot plan over ONE
        local shard (serve.rollup).  Refresh requests carry the prior
        partials plus the chunk-prefix fingerprint they were computed
        against (``rollup_base``): an exact prefix aggregates only the
        appended tail chunks and hostmerges them into the prior — the
        PR-14 delta discipline — while any rewrite/desync (or a windowed
        plan, whose tail execution path differs) rebuilds from scratch.
        The reply ships partials bytes, the refreshed fingerprint, and
        the column census the subsumption proofs need."""
        from bqueryd_tpu.models.query import GroupByQuery, ResultPayload
        from bqueryd_tpu.ops import workingset
        from bqueryd_tpu.parallel import hostmerge
        from bqueryd_tpu.plan import dag as dagmod

        timer = PhaseTimer()
        args, _kwargs = msg.get_args_kwargs()
        filename, groupby_cols, agg_list, where_terms = args[:4]
        rootdir = os.path.join(self.data_dir, filename)
        if not os.path.exists(rootdir):
            raise ValueError(f"Path {rootdir} does not exist")
        table = self._open_table(rootdir)
        dag = None
        if msg.get("dag"):
            dag = dagmod.OperatorDAG.from_wire(msg.get_from_binary("dag"))
            dag.sole_payload = False  # rollups store the mergeable form
            query = dag.plain_groupby_query()
        else:
            query = GroupByQuery(
                groupby_cols, agg_list, where_terms or [], aggregate=True
            )
            dag = dagmod.dag_from_query(query)
            query = dag.plain_groupby_query()

        mode = "rebuild"
        data = None
        prior = (
            msg.get_from_binary("rollup_prior")
            if msg.get("rollup_prior") else None
        )
        base = (
            msg.get_from_binary("rollup_base")
            if msg.get("rollup_base") else None
        )
        if prior is not None and base is not None and query is not None:
            new_ids = workingset.growth_since(base, table)
            if new_ids is not None and not new_ids:
                mode, data = "fresh", prior
            elif new_ids is not None:
                self.engine.timer = timer
                tail_payload = self.engine.execute_local(
                    table.chunk_view(new_ids), query
                )
                with timer.phase("hostmerge"):
                    merged = hostmerge.merge_payloads(
                        [ResultPayload.from_bytes(prior), tail_payload]
                    )
                data = ResultPayload(merged).to_bytes()
                mode = "delta"
        if data is None:
            if query is not None:
                self.engine.timer = timer
                payload = self.engine.execute_local(table, query)
            else:
                payload = self._execute_dag([table], dag, timer)
            with timer.phase("serialize"):
                data = payload.to_bytes()
        self.flight.record(
            "rollup_build", filename=filename, mode=mode,
            bytes=len(data), token=msg.get("token"),
        )
        reply = msg.copy()
        reply.pop("params", None)
        reply.pop("dag", None)
        reply.pop("rollup_prior", None)
        reply.pop("rollup_base", None)
        reply["data"] = data
        reply["rollup_mode"] = mode
        reply["phase_timings"] = timer.as_dict()
        reply.add_as_binary("rollup_base", workingset.table_growth_base(table))
        reply.add_as_binary("rollup_zones", self._rollup_census(table))
        return reply

    def _execute(self, tables, query, timer, strategy=None):
        """Psum-mergeable aggregations (any shard count) -> mesh executor
        (on-device merge + HBM-resident caches); distinct-count / raw-rows
        single shard -> single-device engine; other multi-shard shapes ->
        per-shard engine + host value-keyed merge.  Always returns ONE
        payload per CalcMessage.

        ``strategy`` is the planner's kernel-route hint from the plan
        fragment: "host" skips the mesh outright (the engine path forces the
        NumPy kernels); device routes thread into the mesh program / engine
        dispatch.  Hints never override survival routing — a wedged backend
        still host-routes everything."""
        from bqueryd_tpu.models.query import (
            _host_ns_estimate,
            host_kernel_rows,
        )
        from bqueryd_tpu import ops as ops_mod
        from bqueryd_tpu.parallel import hostmerge
        from bqueryd_tpu.parallel.executor import MeshQueryExecutor

        # what the kernel actually ran post-guards, for the reply envelope /
        # kernel span (satellite: hints used to normalize silently and
        # nothing could tell what executed)
        self._last_effective_strategy = None
        # how this query's partials merged ("device" = ICI-mesh collective,
        # "host" = hostmerge.merge_payloads, "none" = single payload, no
        # merge) — the reply envelope's ``merge_mode`` key
        self._last_merge_mode = None
        # chunk-granular zone-map pruning: a selective filter whose
        # per-chunk min/max prove most chunks unmatchable executes over
        # views of only the surviving chunks — decode, alignment and H2D
        # shrink proportionally.  Basket expansion is excluded (expansion
        # re-selects rows of the same basket living in pruned chunks).
        self._last_chunk_prune = None
        if query.where_terms and not query.expand_filter_column:
            from bqueryd_tpu.ops import predicates

            if predicates.chunk_prune_enabled():
                with timer.phase("prune"):
                    pruned = [
                        predicates.chunk_pruned_table(t, query.where_terms)
                        for t in tables
                    ]
                decoded = sum(p[1] for p in pruned)
                skipped = sum(p[2] for p in pruned)
                if decoded or skipped:
                    tables = [p[0] for p in pruned]
                    self.chunks_decoded_total.inc(decoded)
                    self.chunks_skipped_total.inc(skipped)
                    self._last_chunk_prune = (decoded, skipped)
        total_rows = sum(int(t.nrows) for t in tables)
        # the same per-query cost estimate execute_local uses, worst shard
        # wins — a mismatched (optimistic) rate here would let slow-rated
        # queries skip the mesh executor only to device-dispatch per shard.
        # A wedged accelerator backend skips the mesh outright: the engine
        # path below host-routes everything (host_kernel_rows returns its
        # wedged sentinel) instead of hanging on a device dispatch.
        if strategy != "host" and not devicehealth.backend_wedged(
        ) and MeshQueryExecutor.supports(
            query
        ) and total_rows > host_kernel_rows(
            max(
                (
                    _host_ns_estimate(t, query.agg_list, total_rows)
                    for t in tables
                ),
                default=None,
            )
        ):
            # single shards go through the mesh executor too: its alignment +
            # HBM block caches make repeat queries one kernel dispatch.
            # Queries at or below the host threshold fall through to the
            # per-shard engine path, whose execute_local picks the host
            # kernel (latency-aware routing, models.query.host_kernel_rows).
            self.mesh_executor.timer = timer
            import jax

            try:
                result = self.mesh_executor.execute(
                    tables, query, strategy=strategy
                )
                self._last_effective_strategy = (
                    self.mesh_executor.last_effective_strategy
                )
                self._last_merge_mode = self.mesh_executor.last_merge_mode
                return result
            except ops_mod.CompositeOverflow:
                # the mesh alignment needs radix-packed composites; a key
                # space past int64 degrades to the per-shard engine path,
                # which factorizes key TUPLES instead of refusing the query
                self.logger.info(
                    "composite key space exceeds int64; serving via the "
                    "per-shard engine path"
                )
            except jax.errors.JaxRuntimeError as exc:
                # a failed device program must not fail the query: tunneled
                # backends surface flaky remote-compile INTERNAL errors
                # (observed on hardware: two HTTP-500 compile-helper crashes,
                # TPU_VALIDATE_r5_prefix.json case7/case13) and the engine
                # path compiles different, smaller programs that usually
                # still succeed — worst case ITS error propagates instead
                self.logger.warning(
                    "mesh executor failed (%s); retrying via the per-shard "
                    "engine path",
                    (str(exc).splitlines() or [""])[0][:200],
                )
        if len(tables) == 1:
            self.engine.timer = timer
            result = self.engine.execute_local(
                tables[0], query, strategy=strategy
            )
            self._last_effective_strategy = (
                self.engine.last_effective_strategy
            )
            self._last_merge_mode = "none"  # one payload, nothing merged
            return result
        self.engine.timer = timer
        # pipelined per-shard fallback: shards run on the bounded pipeline
        # pool (BQUERYD_TPU_PIPELINE_THREADS; 1 restores the serial loop),
        # so shard i+1's decode+factorize overlaps shard i's kernel — the
        # engine's caches are lock-protected and map_ordered returns
        # payloads in input order, keeping hostmerge.merge_payloads
        # deterministic (bit-identical to the serial path)
        from bqueryd_tpu.parallel import pipeline

        payloads = pipeline.map_ordered(
            lambda t: self.engine.execute_local(t, query, strategy=strategy),
            tables,
        )
        # shards share one query shape, so the engine's last route speaks
        # for the group (a host/device split across shards reports the last)
        self._last_effective_strategy = self.engine.last_effective_strategy
        self._last_merge_mode = "host"
        with timer.phase("hostmerge"):
            merged = hostmerge.merge_payloads(payloads)
        from bqueryd_tpu.models.query import ResultPayload

        return ResultPayload(merged)

    def _execute_dag(self, tables, dag, timer):
        """Extended operator-DAG execution (joins / top-k / quantile
        sketches / window rollups).  Device-mergeable shapes (classic +
        top-k + sketch part kinds) take the MESH FAST PATH: one
        decode/align/H2D pass over the whole shard group and one compiled
        mesh program whose span-owned collective merge ships only the
        final table (``merge_mode`` "device") — the same execution
        machinery plain groupbys have had since PR 7.  Everything else —
        count_distinct sets, raw rows, object-dtype derived measures,
        over-budget sketch grids, the ``BQUERYD_TPU_DAG_BATCH=0`` /
        ``BQUERYD_TPU_DEVICE_MERGE=0`` kill switches, wedged backends, or
        a failed device program — falls back to the PR-13 per-shard
        operator pipelines on the stage pool with the host value-keyed
        merge.  Plain DAGs never reach here (handle_work routes them
        through ``_execute`` bit-identically)."""
        from bqueryd_tpu.models.query import host_kernel_rows
        from bqueryd_tpu.parallel.opexec import DagExecutor
        from bqueryd_tpu.plan import dag as dagmod

        self._last_chunk_prune = None
        total_rows = sum(int(t.nrows) for t in tables)
        if (
            dagmod.dag_batchable(dag)
            and not devicehealth.backend_wedged()
            and total_rows > host_kernel_rows()
        ):
            import jax

            from bqueryd_tpu import ops as ops_mod
            from bqueryd_tpu.parallel import executor as executor_mod

            self.mesh_executor.timer = timer
            try:
                payload = self.mesh_executor.execute_dag(tables, dag)
                self._last_effective_strategy = (
                    self.mesh_executor.last_effective_strategy
                )
                self._last_merge_mode = (
                    self.mesh_executor.last_merge_mode
                )
                self._fold_chunk_prune(
                    self.mesh_executor.last_prune_counts
                )
                return payload
            except executor_mod.DagFastPathUnsupported as exc:
                self.logger.debug(
                    "DAG fast path unavailable (%s); serving via the "
                    "per-shard pipeline", exc,
                )
            except ops_mod.CompositeOverflow:
                self.logger.info(
                    "composite key space exceeds int64; serving the DAG "
                    "via the per-shard pipeline"
                )
            except jax.errors.JaxRuntimeError as exc:
                self.logger.warning(
                    "DAG mesh program failed (%s); retrying via the "
                    "per-shard pipeline",
                    (str(exc).splitlines() or [""])[0][:200],
                )
        executor = DagExecutor(self.engine)
        payload = executor.execute(tables, dag, timer=timer)
        self._last_effective_strategy = executor.last_effective_strategy
        self._last_merge_mode = executor.last_merge_mode
        self._fold_chunk_prune(executor._prune_counts)
        return payload

    def _fold_chunk_prune(self, prune_counts):
        """Fold a DAG execution's per-shard (decoded, skipped) chunk-prune
        counts into the worker counters + the prune-span tags."""
        decoded = sum(c[0] for c in prune_counts)
        skipped = sum(c[1] for c in prune_counts)
        if decoded or skipped:
            self.chunks_decoded_total.inc(decoded)
            self.chunks_skipped_total.inc(skipped)
            self._last_chunk_prune = (decoded, skipped)

    def _open_table(self, rootdir):
        """Table instances cached by meta identity: re-opening per query
        costs a meta.json parse per shard; activation (fresh inode/mtime)
        misses naturally.  Instances are read-only and light — column bytes
        live in the storage module's global cache, not per instance."""
        from bqueryd_tpu.storage import ctable
        from bqueryd_tpu.storage.ctable import rootdir_cache_key

        key = rootdir_cache_key(rootdir)
        if key is not None:
            hit = self._table_cache.get(key)
            if hit is not None:
                return hit
        table = ctable(rootdir, mode="r", auto_cache=True)
        if key is not None:
            if len(self._table_cache) > 512:
                self._table_cache.clear()
            self._table_cache[key] = table
        return table

    def handle_work(self, msg):
        if msg.isa("execute_code"):
            return self.execute_code(msg)
        if msg.isa("append"):
            return self._append_rows(msg)
        if msg.isa("rollup"):
            return self._rollup_build(msg)
        if not msg.isa("groupby"):
            return super().handle_work(msg)
        if msg.get("bundle"):
            return self._handle_bundle(msg)

        from bqueryd_tpu import obs
        from bqueryd_tpu.models.query import GroupByQuery

        # distributed tracing: phases double as spans (PhaseTimer records
        # into the recorder), the worker's "calc" root span parents to the
        # controller's dispatch span via the envelope TraceContext
        recorder = None
        if obs.enabled():
            ctx = obs.TraceContext.from_wire(msg.get_trace())
            recorder = obs.SpanRecorder(
                trace_id=ctx.trace_id if ctx else obs.new_id(16),
                node=self.worker_id,
                root_name="calc",
                root_parent=ctx.span_id if ctx else None,
            )
        timer = PhaseTimer(recorder=recorder, span_names=obs.PHASE_SPAN_NAMES)
        args, kwargs = msg.get_args_kwargs()
        filename, groupby_cols, agg_list, where_terms = args[:4]
        from bqueryd_tpu.plan import dag as dagmod

        # EVERY groupby now compiles through the operator-DAG layer
        # (plan.dag).  A `dag` envelope key is the authoritative program
        # (the rpc.query verb's richer shapes: joins, top-k, sketches,
        # windows); otherwise the classic fragment/params build a plain
        # DAG, whose plain_groupby_query() round trip is field-exact — the
        # engine path below executes it bit-identically to the pre-DAG
        # sequence (proven over the fuzz corpus).
        dag = None
        if msg.get("dag"):
            dag = dagmod.OperatorDAG.from_wire(msg.get_from_binary("dag"))
            dag.sole_payload = bool(msg.get("sole_shard"))
            query = dag.plain_groupby_query()
            strategy = None
        else:
            # a planning controller ships the compiled plan fragment
            # alongside the reference-shaped params: the fragment is
            # authoritative (it carries the rewritten query + the
            # kernel-strategy hint); bare params keep working for
            # mixed-version clusters and direct tests
            fragment = (
                msg.get_from_binary("plan") if msg.get("plan") else None
            )
            strategy = None
            if fragment:
                from bqueryd_tpu.plan import calibrate, fragment_to_query

                query = fragment_to_query(fragment)
                strategy = fragment.get("strategy")
                if strategy in (None, "auto"):
                    strategy = None
                elif strategy == "matmul" and fragment.get(
                    "strategy_binding"
                ):
                    # calibration-backed promotion rides the wire as
                    # advisory "matmul" + this flag (old workers ignore it
                    # — see plan.logical.fragment_for); reconstruct the
                    # binding form unless BQUERYD_TPU_CALIB=0, the kill
                    # switch that restores pre-calibration behaviour
                    # exactly on this worker even when a calibrating
                    # controller emitted the promotion
                    if calibrate.enabled():
                        strategy = "matmul!"
            else:
                query = GroupByQuery(
                    groupby_cols,
                    agg_list,
                    where_terms or [],
                    aggregate=kwargs.get("aggregate", True),
                    expand_filter_column=kwargs.get("expand_filter_column"),
                    sole_payload=bool(msg.get("sole_shard")),
                )
            # round-trip through the DAG layer: compile, then rebuild the
            # query from the compiled form — the pair is field-exact, so
            # execution (and the result-cache key) stays bit-identical
            dag = dagmod.dag_from_query(query)
            query = dag.plain_groupby_query()
        filenames = filename if isinstance(filename, list) else [filename]
        tables = []
        with timer.phase("open"):
            for name in filenames:
                rootdir = os.path.join(self.data_dir, name)
                if not os.path.exists(rootdir):
                    raise ValueError(f"Path {rootdir} does not exist")
                tables.append(self._open_table(rootdir))
        cache = self.result_cache
        cache_key = None
        data = None
        if cache is not None:
            from bqueryd_tpu.parallel.executor import _table_key

            cache_key = (
                tuple(_table_key(t) for t in tables),
                # extended DAGs have no GroupByQuery form; their identity
                # is the DAG signature (join table / window / sketch
                # params included).  Plain shapes keep the historical
                # query-signature key, so warm caches survive the DAG
                # refactor untouched.
                query.signature() if query is not None else dag.signature(),
            )
            data = cache.get(cache_key)
            if data is not None:
                timer.timings["result_cache"] = 0.0
        mem_tags = None
        # a result-cache hit compiled nothing: "cached" keeps the reply's
        # route report honest instead of silently dropping the key
        effective = "cached" if data is not None else None
        # delta-maintained serving: on a result-cache miss for a
        # delta-eligible shape, try refreshing a cached result by
        # aggregating ONLY the chunks appended since it was computed
        # (ops.workingset; "delta" in the route report)
        delta_cache = None
        delta_key = None
        if query is not None and self._delta_eligible(query):
            delta_cache = self.delta_cache()
            if delta_cache is not None:
                delta_key = self._delta_key(tables, query)
        if data is None and delta_cache is not None:
            self._last_merge_mode = None
            data = self._serve_delta(delta_cache, tables, query, timer)
            if data is not None:
                effective = "delta"
                if cache is not None and len(data) <= cache.max_bytes // 8:
                    cache.put(cache_key, data, nbytes=len(data))
        merge_mode = (
            getattr(self, "_last_merge_mode", None)
            if effective == "delta" else None
        )  # otherwise only freshly computed queries merged anything
        if data is None:
            import contextlib

            from bqueryd_tpu.obs import profile as obs_profile

            profile_dir = os.environ.get("BQUERYD_TPU_PROFILE_DIR")
            # opt-in: capture a full TensorBoard trace of this query
            if profile_dir:
                from bqueryd_tpu.utils.tracing import profiler_trace

                profiling = profiler_trace(profile_dir)
            else:
                profiling = contextlib.nullcontext()
            mem_before = obs_profile.profiler().memory_sample()
            with profiling:
                if query is not None:
                    # plain shape: the unchanged engine/mesh path —
                    # bit-identical to the pre-DAG hardwired sequence
                    payload = self._execute(
                        tables, query, timer, strategy=strategy
                    )
                else:
                    payload = self._execute_dag(tables, dag, timer)
            effective = getattr(self, "_last_effective_strategy", None)
            merge_mode = getattr(self, "_last_merge_mode", None)
            if recorder is not None and effective:
                # the kernel span carries what the executor actually
                # compiled post-guards — rpc.trace() waterfalls can now
                # tell a promoted matmul from a silently-normalized hint
                for span in recorder.spans:
                    if span.get("name") == "kernel":
                        span.setdefault("tags", {})[
                            "effective_strategy"
                        ] = effective
            if recorder is not None and self._last_chunk_prune:
                # zone-map pruning effect on the trace: the prune span
                # says how many chunks the decode stages never touched
                decoded_n, skipped_n = self._last_chunk_prune
                for span in recorder.spans:
                    if span.get("name") == "prune":
                        tags = span.setdefault("tags", {})
                        tags["chunks_decoded"] = decoded_n
                        tags["chunks_skipped"] = skipped_n
                        break
            # the execute above is proof the backend answered: safe to
            # (lazily) enumerate devices for HBM sampling from now on
            obs_profile.profiler().note_devices()
            mem_after = obs_profile.profiler().memory_sample()
            if mem_after is not None:
                # device-memory attribution on the calc root span (visible
                # in rpc.trace waterfalls).  peak_bytes_in_use is the
                # allocator's PROCESS-LIFETIME watermark, so it is reported
                # as exactly that; the per-QUERY attribution is the pair of
                # deltas — how much this query raised the watermark, and
                # what it added to live device memory
                before = mem_before or mem_after
                mem_tags = {
                    "device_hbm_watermark_bytes":
                        mem_after["peak_bytes_in_use"],
                    "device_peak_delta_bytes": (
                        mem_after["peak_bytes_in_use"]
                        - before["peak_bytes_in_use"]
                    ),
                    "device_bytes_delta": (
                        mem_after["bytes_in_use"] - before["bytes_in_use"]
                    ),
                }
            with timer.phase("serialize"):
                data = payload.to_bytes()
            if cache is not None and len(data) <= cache.max_bytes // 8:
                cache.put(cache_key, data, nbytes=len(data))
            if delta_cache is not None:
                # record the delta base: the snapshots of the very table
                # instances this result was computed from, so a later
                # append refreshes it from the tail alone
                delta_cache.store(delta_key, tables, data)
        if obs.enabled():
            # result-payload size per reply — observed for cache hits too,
            # so this histogram and its controller-side twin
            # (reply_payload_bytes) count the same replies and the bench's
            # merge section can cross-check them
            self.reply_bytes.observe(len(data))
        # a result comparable to the worker's memory budget (1/32 of the
        # restart limit, 64 MB at the default 2 GB) means the query caches
        # are the next thing to evict
        if self.memory_limit_mb and sys.getsizeof(data) > (
            self.memory_limit_mb * (1 << 20) // 32
        ):
            self._shed_caches()
        reply = msg.copy()
        # the reply must not echo the request's DAG (the broadcast join
        # ships the whole dimension table under that key — re-shipping it
        # worker->controller per shard reply is pure wire waste; the
        # controller only consults the key on ERROR replies, which keep it)
        reply.pop("dag", None)
        reply["data"] = data
        reply["phase_timings"] = timer.as_dict()
        if recorder is not None:
            # the span list rides the JSON reply; the controller folds it
            # into the query timeline behind rpc.trace(trace_id); device
            # memory attribution tags the calc root span
            reply["spans"] = recorder.export(tags=mem_tags)
            self.groupby_queries.inc()
            self.groupby_seconds.observe(timer.total())
            self._observe_phase_histograms(timer)
        # deadline propagation: the reply keeps the envelope's ``deadline``
        # (msg.copy) and reports the budget left after execution
        remaining = msg.deadline_remaining()
        if remaining is not None:
            reply["deadline_remaining"] = round(remaining, 4)
        if strategy is not None:
            reply["strategy"] = strategy
        if effective is not None:
            # post-guard reality, distinct from the hint: declared in
            # messages.RESULT_ENVELOPE_SCHEMA/ENVELOPE_SCHEMA, folded by the
            # controller into the client result envelope and bench's
            # chosen_strategy
            reply["effective_strategy"] = effective
        if merge_mode is not None:
            # how this reply's partials merged: "device" (ICI-mesh
            # collective, final table only fetched), "host"
            # (hostmerge.merge_payloads — the kill switch / non-mergeable
            # fallback), or "none" (single payload).  Declared in
            # messages.ENVELOPE_SCHEMA; the controller folds it into the
            # client result envelope's merge_modes
            reply["merge_mode"] = merge_mode
        self.logger.debug("calc %s done: %s", filename, timer.as_dict())
        return reply

    def _observe_phase_histograms(self, timer):
        """One ``bqueryd_tpu_query_phase_seconds{phase=...}`` observation
        per timed phase — the single registration site both groupby reply
        paths (solo and bundle) share, so the family's help text and label
        mapping can never diverge between them."""
        from bqueryd_tpu import obs

        for phase, seconds in timer.timings.items():
            self.metrics.histogram(
                "bqueryd_tpu_query_phase_seconds",
                "per-phase worker latency (storage decode, H2D, "
                "kernel, merge, ...)",
                labels={"phase": obs.PHASE_SPAN_NAMES.get(phase, phase)},
            ).observe(seconds)

    def _bundle_mesh_eligible(self, tables, queries):
        """Mirror of the single-query ``_execute`` routing decision for a
        whole bundle: the shared-scan mesh path runs when every member is
        mergeable, the backend is healthy, and the row count clears the
        host-kernel threshold (worst member's rate estimate wins)."""
        from bqueryd_tpu.models.query import (
            _host_ns_estimate,
            host_kernel_rows,
        )
        from bqueryd_tpu.parallel.executor import MeshQueryExecutor

        if devicehealth.backend_wedged():
            return False
        if not all(MeshQueryExecutor.supports(q) for q in queries):
            return False
        total_rows = sum(int(t.nrows) for t in tables)
        try:
            worst = max(
                (
                    _host_ns_estimate(t, q.agg_list, total_rows)
                    for t in tables
                    for q in queries
                ),
                default=None,
            )
        except Exception:
            # an unestimable member (e.g. a column the shard doesn't have)
            # routes the bundle to the per-member path, where the offender
            # errors ALONE instead of failing its bundle-mates
            return False
        return total_rows > host_kernel_rows(worst)

    def _handle_bundle(self, msg):
        """Shared-scan bundle execution: one CalcMessage carrying several
        compatible member queries (``plan.bundle``).  Scan work — open,
        decode, align/factorize, uploads — happens once; each member keeps
        its own identity: per-member result-cache keys, per-member deadline
        enforcement (an expired member is dropped from the stack, not the
        bundle), per-member error isolation on the fallback path.  The
        reply demultiplexes through the ``bundle_members`` wire key: its
        data frame is one pickled ``{"payloads": {member_id: bytes},
        "errors": {member_id: text}}`` envelope."""
        import pickle

        from bqueryd_tpu import chaos, obs
        from bqueryd_tpu.parallel.executor import _table_key
        from bqueryd_tpu.plan import bundle as bundlemod

        recorder = None
        if obs.enabled():
            ctx = obs.TraceContext.from_wire(msg.get_trace())
            recorder = obs.SpanRecorder(
                trace_id=ctx.trace_id if ctx else obs.new_id(16),
                node=self.worker_id,
                root_name="calc",
                root_parent=ctx.span_id if ctx else None,
            )
        timer = PhaseTimer(recorder=recorder, span_names=obs.PHASE_SPAN_NAMES)
        fragment = msg.get_from_binary("bundle")
        members = bundlemod.bundle_to_queries(fragment)
        strategy = bundlemod.fragment_strategy(fragment)
        filename = msg.get("filename") or fragment.get("filenames")
        filenames = filename if isinstance(filename, list) else [filename]
        tables = []
        with timer.phase("open"):
            for name in filenames:
                rootdir = os.path.join(self.data_dir, name)
                if not os.path.exists(rootdir):
                    raise ValueError(f"Path {rootdir} does not exist")
                tables.append(self._open_table(rootdir))

        cache = self.result_cache
        tables_sig = tuple(_table_key(t) for t in tables)
        payloads = {}      # member_id -> serialized ResultPayload bytes
        errors = {}        # member_id -> failure text (member-only abort)
        active = []        # (member_id, query) still needing execution
        now = time.time()
        for member_id, deadline, query in members:
            if deadline is not None and float(deadline) <= now:
                # the member's budget is gone: drop it from the stack, not
                # the bundle — its bundle-mates keep their answers
                errors[member_id] = (
                    f"deadline exceeded "
                    f"{now - float(deadline):.3f}s before execution"
                )
                continue
            if cache is not None:
                hit = cache.get((tables_sig, query.signature()))
                if hit is not None:
                    payloads[member_id] = hit
                    continue
            active.append((member_id, query))

        # per-member segment shares (messages.py `member_shares`): measured
        # walls on the fallback path, an equal split on the one-program
        # mesh path; cached members report 0.0 (they consumed no scan)
        cached_ids = list(payloads)
        member_walls = {}
        results = {}
        if active:
            queries = [q for _mid, q in active]
            mesh_payloads = None
            if self._bundle_mesh_eligible(tables, queries):
                import jax

                from bqueryd_tpu import ops as ops_mod

                try:
                    mesh_payloads = self.mesh_executor_for_bundle(
                        tables, queries, timer, strategy
                    )
                except chaos.TransientError:
                    # a transient device fault fails the whole bundle over
                    # to a replica holder — never silently degrades one
                    # member
                    raise
                except (
                    ops_mod.CompositeOverflow,
                    jax.errors.JaxRuntimeError,
                ) as exc:
                    self.logger.warning(
                        "bundle mesh path failed (%s); retrying members "
                        "via the per-member engine path",
                        (str(exc).splitlines() or [""])[0][:200],
                    )
                except ValueError as exc:
                    # a member-shape rejection (e.g. datetime sum) must
                    # isolate to the per-member path, where the offender
                    # errors alone
                    self.logger.info(
                        "bundle mesh path rejected (%s); running members "
                        "individually", exc,
                    )
            if mesh_payloads is not None:
                results = dict(zip((m for m, _q in active), mesh_payloads))
            else:
                for member_id, query in active:
                    try:
                        exec_clock = time.perf_counter()
                        results[member_id] = self._execute(
                            tables, query, timer, strategy=strategy
                        )
                        member_walls[member_id] = (
                            time.perf_counter() - exec_clock
                        )
                    except chaos.TransientError:
                        raise  # whole-bundle failover, as above
                    except Exception as exc:
                        self.logger.exception(
                            "bundle member %s failed", member_id
                        )
                        errors[member_id] = (
                            f"{type(exc).__name__}: {exc}"
                        )

        with timer.phase("serialize"):
            for member_id, payload in results.items():
                data = payload.to_bytes()
                payloads[member_id] = data
                if cache is not None and len(data) <= cache.max_bytes // 8:
                    query = next(
                        q for mid, q in active if mid == member_id
                    )
                    cache.put(
                        (tables_sig, query.signature()), data,
                        nbytes=len(data),
                    )
            data = pickle.dumps(
                {"v": 1, "payloads": payloads, "errors": errors},
                protocol=4,
            )
        if obs.enabled():
            self.reply_bytes.observe(len(data))
        # same memory backstop as the solo reply path — a bundle envelope
        # is ~N solo payloads in one message, the LARGEST reply this
        # worker produces, so the cache shed matters here most
        if self.memory_limit_mb and sys.getsizeof(data) > (
            self.memory_limit_mb * (1 << 20) // 32
        ):
            self._shed_caches()
        reply = msg.copy()
        reply["data"] = data
        reply["bundle_members"] = [mid for mid, _dl, _q in members]
        reply["member_shares"] = {
            **{mid: 0.0 for mid in cached_ids},
            **bundlemod.member_shares(list(results), walls=member_walls),
        }
        reply["phase_timings"] = timer.as_dict()
        if recorder is not None:
            reply["spans"] = recorder.export()
            # one CalcMessage executed, whatever its member count (the
            # counter's help text promise); member volume is the
            # controller's plan_bundled_queries
            self.groupby_queries.inc()
            self.groupby_seconds.observe(timer.total())
            # same per-phase histograms as the solo reply path: with the
            # window on, bundles ARE the dominant serving path — a phase
            # regression there must not vanish from the very histograms
            # built to catch it
            self._observe_phase_histograms(timer)
        # route/merge visibility mirrors the single-query reply: the last
        # executed route speaks for the bundle (members share one shape);
        # "cached" only when cache hits actually served members — a bundle
        # whose members ALL errored pre-execution served nothing
        effective = (
            getattr(self, "_last_effective_strategy", None)
            if active
            else ("cached" if payloads else None)
        )
        merge_mode = (
            getattr(self, "_last_merge_mode", None) if active else None
        )
        if effective is not None:
            reply["effective_strategy"] = effective
        if merge_mode is not None:
            reply["merge_mode"] = merge_mode
        self.logger.debug(
            "bundle calc %s done: %d members (%d cached/served, %d "
            "errored): %s",
            filename, len(members),
            len(payloads) - len(results), len(errors), timer.as_dict(),
        )
        return reply

    def mesh_executor_for_bundle(self, tables, queries, timer, strategy):
        """Run the shared-scan mesh path for a bundle (seam kept separate
        so tests can spy on it): returns per-member ResultPayloads."""
        self._last_effective_strategy = None
        self._last_merge_mode = None
        self.mesh_executor.timer = timer
        payloads = self.mesh_executor.execute_bundle(
            tables, queries, strategy=strategy
        )
        self._last_effective_strategy = (
            self.mesh_executor.last_effective_strategy
        )
        self._last_merge_mode = self.mesh_executor.last_merge_mode
        return payloads

    def execute_code(self, msg):
        """Import a dotted function path and call it — the reference's
        deliberate remote-execution feature for trusted clusters (reference
        bqueryd/worker.py:250-267, warned in reference README.md:129).
        Gated: set BQUERYD_TPU_ENABLE_EXECUTE_CODE=1 to enable."""
        if os.environ.get("BQUERYD_TPU_ENABLE_EXECUTE_CODE") != "1":
            raise PermissionError(
                "execute_code disabled; set BQUERYD_TPU_ENABLE_EXECUTE_CODE=1"
            )
        args, kwargs = msg.get_args_kwargs()
        function = msg.get("function") or kwargs.pop("function", None)
        if not function:
            raise ValueError("execute_code needs a function=module.path.fn")
        # reference calling convention (reference bqueryd/worker.py:250-267):
        # the function's positional/keyword args travel as the RPC kwargs
        # `args=[...]` / `kwargs={...}`
        call_args = kwargs.pop("args", None) or list(args)
        call_kwargs = kwargs.pop("kwargs", None) or {}
        # any other keywords are the function's own (direct-kwarg convention)
        call_kwargs = {**kwargs, **call_kwargs}
        module_name, _, fn_name = function.rpartition(".")
        fn = getattr(importlib.import_module(module_name), fn_name)
        result = fn(*call_args, **call_kwargs)
        reply = msg.copy()
        reply.add_as_binary("result", result)
        return reply


class DownloaderNode(WorkerBase):
    """Ticket-driven blob downloader (reference bqueryd/worker.py:351-567).
    Full pipeline logic in bqueryd_tpu.download (phase: distribution).

    Fetches run on a small thread pool (the reference ran 3 downloader
    *processes* per box, reference misc/supervisor.conf) so a slow or hung
    blob stream never blocks the event loop: ticket polling, WRM heartbeats,
    and cancellation stay live during long downloads.  Pool threads never
    touch the zmq socket — controller notifications go through a thread-safe
    outbox drained by the event loop."""

    workertype = "download"

    def __init__(self, *args, **kw):
        download_threads = kw.pop("download_threads", None)
        kw.setdefault("heartbeat_interval", DEFAULT_HEARTBEAT_INTERVAL)
        super().__init__(*args, **kw)
        self.download_interval = DOWNLOAD_DELAY
        self._last_download_check = 0.0
        if download_threads is None:
            download_threads = int(
                os.environ.get("BQUERYD_TPU_DOWNLOAD_THREADS", "3")
            )
        self.download_threads = max(1, download_threads)
        self._download_pool = None
        self.downloads_done = self.metrics.counter(
            "bqueryd_tpu_downloads_total",
            "download tickets completed by this node",
        )
        self.downloads_failed = self.metrics.counter(
            "bqueryd_tpu_download_failures_total",
            "download tickets failed terminally by this node",
        )
        import queue

        self._outbox = queue.Queue()

    @property
    def download_pool(self):
        if self._download_pool is None:
            from concurrent.futures import ThreadPoolExecutor

            self._download_pool = ThreadPoolExecutor(
                max_workers=self.download_threads,
                thread_name_prefix=f"dl-{self.worker_id[:6]}",
            )
        return self._download_pool

    def heartbeat(self):
        super().heartbeat()
        self._drain_outbox()
        now = time.time()
        if now - self._last_download_check >= self.download_interval:
            self._last_download_check = now
            try:
                self.check_downloads()
            except Exception:
                self.logger.exception("error checking downloads")

    def _drain_outbox(self):
        """Send controller notifications queued by pool threads (zmq sockets
        are single-thread-only, so only the event loop may send)."""
        import queue

        while True:
            try:
                msg = self._outbox.get_nowait()
            except queue.Empty:
                return
            self.send_to_all(msg)

    def stop(self):
        if self._request_stop_only():
            return  # outbox/socket teardown belongs to the loop thread
        if self._download_pool is not None:
            self._download_pool.shutdown(wait=False, cancel_futures=True)
        self._drain_outbox()
        super().stop()

    def check_downloads(self):
        from bqueryd_tpu.download import check_downloads

        check_downloads(self)

    def run_download(self, ticket, fileurl, lock):
        """Run one claimed download on the pool; the claim lock is held for
        the download's lifetime and released by the pool thread."""

        def job():
            try:
                self.download_file(ticket, fileurl, lock=lock)
            except Exception as exc:
                self.logger.exception("download %s failed", fileurl)
                self.fail_ticket(ticket, fileurl, str(exc))
            finally:
                lock.release()

        self.download_pool.submit(job)

    def download_file(self, ticket, fileurl, lock=None):
        from bqueryd_tpu.download import download_file

        download_file(self, ticket, fileurl, lock=lock)

    def file_downloader_progress(self, ticket, fileurl, progress):
        from bqueryd_tpu.download import set_progress

        set_progress(self.store, self.node_name, ticket, fileurl, progress)

    def remove_ticket(self, ticket):
        from bqueryd_tpu.download import remove_ticket

        remove_ticket(self, ticket)
        self.downloads_done.inc()
        self._outbox.put(TicketDoneMessage({"ticket": ticket}))

    def fail_ticket(self, ticket, fileurl, error):
        """Terminal download failure: poison the ticket (ERROR slot blocks
        activation on every node) and tell controllers so waiting clients get
        the error instead of the reference's false DONE."""
        from bqueryd_tpu.download import fail_ticket

        fail_ticket(self, ticket, fileurl, error)
        self.downloads_failed.inc()
        self._outbox.put(
            TicketDoneMessage({"ticket": ticket, "error": str(error)})
        )


class MoveBcolzNode(DownloaderNode):
    """Second phase of the two-phase distribute commit: flips downloaded
    shards into the serving dir only when every node finished (reference
    bqueryd/worker.py:570-637)."""

    workertype = "movebcolz"

    def check_downloads(self):
        from bqueryd_tpu.download import check_moves

        check_moves(self)
