"""Accelerator-backend health: detect a wedged device without ever hanging.

The failure mode this exists for was observed on this project's own dev
backend: the tunneled TPU stops answering and ``jax.devices()`` (or any
dispatch) blocks forever INSIDE native code — no signal can interrupt it,
so any thread that touches the device is lost.  The reference stack never
had this problem (its compute was host-only, reference bqueryd/worker.py);
a framework whose hot path is an accelerator needs an answer or a single
dead tunnel wedges every worker loop that routes a query to the device.

Strategy: all device liveness questions are answered by SACRIFICIAL daemon
threads.  A probe thread runs one trivial jitted dispatch + fetch; the
asking thread waits at most a deadline and never joins the probe — a hung
probe thread parks on the dead backend forever (daemon: it cannot block
process exit) while callers see the backend latched as wedged.  Routing
then sends every query the host kernels can serve to the host
(:func:`bqueryd_tpu.models.query.host_kernel_rows` returns its cap), and
device-only queries fail fast with a clear error instead of hanging the
worker loop.  A later successful probe unlatches, so a recovered tunnel
resumes device serving without a restart.

At most one probe is ever in flight; a wedged backend costs one parked
thread per probe attempt, rate-limited to the recheck interval.
"""

import os
import threading
import time

_lock = threading.Lock()
_wedged = False
_probe_started = None     # monotonic start of the in-flight probe, or None
_last_probe_start = 0.0   # start of the most recent probe, any outcome
_abandoned = 0            # probes written off as hung since the last success
_generation = 0           # incremented on every not-wedged -> wedged flip

#: past this many parked probe threads, relaunch only every 10 intervals —
#: a permanently dead backend must not grow a thread per interval forever
_MAX_ABANDONED_FAST = 16


def probe_timeout_s():
    """Deadline for one trivial dispatch + fetch.  Generous: a tunneled
    first compile of even ``x + 1`` takes seconds, and a real wedge hangs
    for minutes — 60 s cleanly separates the two.  ``0`` disables wedge
    detection entirely (no probes, never latched): for benchmarks or
    debugging where a hang is preferable to a silent host fallback."""
    return float(os.environ.get("BQUERYD_TPU_DEVICE_PROBE_TIMEOUT_S", 60))


def _recheck_interval_s():
    return float(
        os.environ.get("BQUERYD_TPU_DEVICE_PROBE_INTERVAL_S", 30)
    )


def _default_probe():
    """One trivial jitted dispatch + host fetch on the default backend."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    np.asarray(jax.jit(lambda x: x + 1)(jnp.zeros(())))


#: test seam: replaced to simulate a wedged backend without real hangs
_probe_fn = _default_probe


def _latch_locked():
    """Set the latch (under _lock) and bump the generation on the
    not-wedged -> wedged transition — the single place the rule lives."""
    global _wedged, _generation
    if not _wedged:
        _generation += 1
    _wedged = True


def _probe_body(my_start):
    global _probe_started, _wedged, _abandoned
    try:
        _probe_fn()
    except Exception:
        # a probe that ERRORS (backend gone vs hung) still answered within
        # the deadline, but the device is unusable: latch wedged; the
        # interval clock keeps re-probes coming so recovery is automatic
        with _lock:
            if _probe_started == my_start:
                _probe_started = None
            _latch_locked()
        return
    with _lock:
        # an abandoned probe that finally returns after the tunnel
        # recovers is still good news: any success unlatches
        if _probe_started == my_start:
            _probe_started = None
        _wedged = False
        _abandoned = 0


def _start_probe_locked():
    global _probe_started, _last_probe_start
    _probe_started = _last_probe_start = time.monotonic()
    threading.Thread(
        target=_probe_body,
        args=(_probe_started,),
        name="bqueryd-device-probe",
        daemon=True,
    ).start()


def backend_wedged(launch=True):
    """Whether the default backend is currently latched as wedged.

    Never blocks: state transitions ride the background probes.  An
    in-flight probe past the deadline flips the latch AND writes the probe
    off as hung, so the interval clock keeps launching fresh probes — a
    recovered tunnel unlatches within interval + one dispatch even though
    the original hung thread never returns.  Past ``_MAX_ABANDONED_FAST``
    written-off probes the relaunch cadence drops to every 10 intervals
    (a permanently dead backend must not leak a thread per interval).

    ``launch=False`` reads the latch without ever starting a probe: for
    callers in processes that may have no device intent at all (e.g. the
    routing threshold under an operator env pin), where spawning a JAX
    dispatch thread as a side effect would be wrong.  Such processes can
    only see the latch set by their own failed device calls — which is
    exactly the right scope."""
    global _probe_started, _abandoned
    if probe_timeout_s() <= 0:
        return False  # detection disabled: never latched, no probes
    now = time.monotonic()
    with _lock:
        if _probe_started is not None:
            if now - _probe_started > probe_timeout_s():
                _latch_locked()
                # write the hung probe off so the clock can relaunch
                _probe_started = None
                _abandoned += 1
        elif launch:
            interval = _recheck_interval_s()
            if _abandoned >= _MAX_ABANDONED_FAST:
                interval *= 10
            if now - _last_probe_start > interval:
                _start_probe_locked()
        return _wedged


def run_with_deadline(fn, timeout_s):
    """Run ``fn`` in a sacrificial daemon thread; return ``(done, result)``.

    ``done`` is False when the deadline passed — the thread is abandoned
    (parked on the dead backend), never joined, and its eventual result is
    discarded.  Exceptions inside ``fn`` count as done with result None."""
    box = {}
    ev = threading.Event()

    def body():
        try:
            box["result"] = fn()
        except Exception:
            box["result"] = None
        finally:
            ev.set()

    threading.Thread(target=body, daemon=True).start()
    if ev.wait(timeout_s):
        return True, box.get("result")
    return False, None


def latch_wedged():
    """Latch the backend as wedged on direct evidence (a device call that
    blew its deadline, e.g. the dispatch-floor measurement).  The interval
    clock keeps probing, so recovery stays automatic."""
    with _lock:
        _latch_locked()


def wedge_marker():
    """Snapshot for evidence windows: ``(generation, currently_wedged)``.
    A measurement window is CLEAN iff the marker is identical before and
    after AND neither end is wedged — a transient wedge that recovered
    mid-window bumps the generation even though both endpoint reads of
    ``backend_wedged`` say False."""
    with _lock:
        return (_generation, _wedged)


def window_dirty(start_marker, end_marker=None):
    """Whether a wedge overlapped the window between two markers."""
    if end_marker is None:
        end_marker = wedge_marker()
    return (
        start_marker != end_marker or start_marker[1] or end_marker[1]
    )


def health_snapshot():
    """Gauge-friendly state for the observability registry: read-only (never
    launches a probe — metric scrapes must not spawn device dispatch threads
    as a side effect).  ``{"wedged": 0/1, "abandoned_probes": n,
    "wedge_generation": n}``."""
    with _lock:
        return {
            "wedged": 1 if _wedged else 0,
            "abandoned_probes": _abandoned,
            "wedge_generation": _generation,
        }


def force_state(wedged):
    """Test seam: pin the latch without probing (also resets the interval
    clock so the next ``backend_wedged`` call does not immediately launch
    a real probe under a pinned state)."""
    global _wedged, _probe_started, _last_probe_start, _abandoned
    with _lock:
        _wedged = bool(wedged)
        _probe_started = None
        _last_probe_start = time.monotonic()
        _abandoned = 0
