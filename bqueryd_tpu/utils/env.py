"""Shared env-override parsing for registered ``BQUERYD_TPU_*`` knobs.

One parse site (and one lint pragma) instead of a per-module copy: an
unset, empty, or unparseable override always falls back to the caller's
default — a typo'd value must degrade to the shipped constant, never take
a node down at construction time.  Stdlib-only and import-light: the
jax-free controller reads its timing knobs through here.
"""

import os


def env_num(name, default, cast=float):
    """The registered override when set and parseable, ``default``
    otherwise."""
    # bqtpu: allow[config-dynamic-env-key] callers pass literal registered names: the controller timing knobs (DEAD_WORKER/DISPATCH/DISPATCH_HARD TIMEOUTs, MAX_DISPATCH_RETRIES, HEDGE_MS, REPLICA_FACTOR), plan.admission's ADMIT_* trio, and plan.bundle's BATCH_WINDOW_MS/BATCH_MAX; all in ENV_REGISTRY
    raw = os.environ.get(name)
    if raw in (None, ""):
        return default
    try:
        return cast(raw)
    except (ValueError, TypeError):
        return default
