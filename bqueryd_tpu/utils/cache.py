"""Byte-capped caches shared by the storage and executor layers."""

import threading


class BytesCappedCache:
    """Dict-shaped cache with a byte budget and wholesale eviction.

    Wholesale (clear-everything) eviction is deliberate: entries are
    query-working-set artifacts that re-warm in one pass, and tracking LRU
    order costs more than re-warming does.  The in-memory analogue of
    bquery's auto_cache policy (reference bqueryd/worker.py:291,330).
    Thread-safe: workers share one instance across request threads.
    """

    def __init__(self, max_bytes, sizeof=lambda v: v.nbytes):
        self.max_bytes = int(max_bytes)
        self._sizeof = sizeof
        self._data = {}
        self._bytes = 0
        self._lock = threading.Lock()

    def get(self, key):
        with self._lock:
            return self._data.get(key)

    def put(self, key, value, nbytes=None):
        size = self._sizeof(value) if nbytes is None else nbytes
        with self._lock:
            if key in self._data:
                return
            if self._bytes + size > self.max_bytes:
                self._data.clear()
                self._bytes = 0
            self._data[key] = value
            self._bytes += size

    def clear(self):
        with self._lock:
            self._data.clear()
            self._bytes = 0

    @property
    def nbytes(self):
        return self._bytes

    def __len__(self):
        return len(self._data)

    def __contains__(self, key):
        with self._lock:
            return key in self._data
