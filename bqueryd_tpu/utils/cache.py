"""Byte-capped caches shared by the storage and executor layers."""

import threading


class BytesCappedCache:
    """Dict-shaped cache with a byte budget and LRU segment eviction.

    Entries evict least-recently-used-first, one at a time, until the new
    entry fits — the in-memory analogue of bquery's auto_cache policy
    (reference bqueryd/worker.py:291,330), upgraded from the original
    wholesale clear: a working set larger than one entry no longer loses
    everything when a single insert tips the budget, and an entry larger
    than the whole budget is REJECTED instead of being inserted into a
    permanently over-budget cache.

    ``get`` refreshes recency.  Hit/miss/eviction/rejection counts are
    exposed for the working-set metrics (:mod:`bqueryd_tpu.ops.workingset`)
    and the bench's cache-hit-rate section.  Thread-safe: workers share one
    instance across request threads.
    """

    #: lock discipline, statically checked by bqueryd_tpu.analysis
    #: (rule lock-unguarded-attr): these attributes may only be touched
    #: inside ``with self._lock`` (or in ``*_locked`` helpers)
    _bqtpu_guarded_ = {
        "_lock": (
            "_data", "_sizes", "_bytes",
            "hits", "misses", "evictions", "rejected",
        ),
    }

    def __init__(self, max_bytes, sizeof=lambda v: v.nbytes):
        self.max_bytes = int(max_bytes)
        self._sizeof = sizeof
        self._data = {}      # insertion/recency-ordered (dict is ordered)
        self._sizes = {}     # key -> accounted bytes
        self._bytes = 0
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.evictions = 0   # entries dropped to make room (monotonic)
        self.rejected = 0    # oversize entries refused outright (monotonic)

    def get(self, key):
        with self._lock:
            if key in self._data:
                # refresh recency: move to the MRU end
                value = self._data.pop(key)
                self._data[key] = value
                self.hits += 1
                return value
            self.misses += 1
            return None

    def _evict_lru_locked(self):
        key, _ = next(iter(self._data.items()))
        self._data.pop(key)
        self._bytes -= self._sizes.pop(key)
        self.evictions += 1

    def put(self, key, value, nbytes=None):
        size = int(self._sizeof(value) if nbytes is None else nbytes)
        with self._lock:
            if key in self._data:
                return
            if size > self.max_bytes:
                # inserting would leave the cache over budget however much
                # is evicted: refuse (the caller recomputes, nothing breaks)
                self.rejected += 1
                return
            while self._bytes + size > self.max_bytes and self._data:
                self._evict_lru_locked()
            self._data[key] = value
            self._sizes[key] = size
            self._bytes += size

    def delete(self, key):
        """Drop one entry (no-op when absent); returns whether it existed.
        Not counted as an eviction — deletions are caller-driven
        invalidation (e.g. a delta-cache entry whose table was rewritten),
        not budget pressure."""
        with self._lock:
            if key not in self._data:
                return False
            self._data.pop(key)
            self._bytes -= self._sizes.pop(key)
            return True

    def evict_bytes(self, target_bytes):
        """Evict LRU entries until at least ``target_bytes`` of accounted
        cache bytes are freed (or the cache is empty).  Returns
        ``(bytes_freed, entries_evicted)`` — counted inside the lock so the
        memory-pressure caller
        (:meth:`bqueryd_tpu.ops.workingset.WorkingSet.evict_under_pressure`)
        never misattributes a concurrent capacity eviction."""
        freed = 0
        count = 0
        with self._lock:
            while freed < target_bytes and self._data:
                key, _ = next(iter(self._data.items()))
                self._data.pop(key)
                freed += self._sizes.pop(key)
                count += 1
                self.evictions += 1
            self._bytes -= freed
        return freed, count

    def clear(self):
        with self._lock:
            self._data.clear()
            self._sizes.clear()
            self._bytes = 0

    def stats(self):
        """JSON-safe counters snapshot (hit rate left to the reader so the
        snapshot stays raw-mergeable)."""
        with self._lock:
            return {
                "entries": len(self._data),
                "bytes": self._bytes,
                "max_bytes": self.max_bytes,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "rejected": self.rejected,
            }

    @property
    def nbytes(self):
        with self._lock:
            return self._bytes

    def __len__(self):
        with self._lock:
            return len(self._data)

    def __contains__(self, key):
        with self._lock:
            return key in self._data
