from bqueryd_tpu.utils.fs import mkdir_p, rm_file_or_dir
from bqueryd_tpu.utils.net import (
    bind_to_random_port,
    get_my_ip,
    show_workers,
    tree_checksum,
    zip_to_file,
)
from bqueryd_tpu.utils.tracing import PhaseTimer, trace_span

__all__ = [
    "mkdir_p",
    "rm_file_or_dir",
    "bind_to_random_port",
    "get_my_ip",
    "show_workers",
    "tree_checksum",
    "zip_to_file",
    "PhaseTimer",
    "trace_span",
]
