"""Filesystem helpers (capability match for reference bqueryd/tool.py:1-27)."""

import os
import shutil


def mkdir_p(path):
    """Idempotent recursive mkdir."""
    os.makedirs(path, exist_ok=True)


def rm_file_or_dir(path):
    """Remove a file, directory tree, or symlink if it exists; no-op otherwise."""
    if path is None or not os.path.lexists(path):
        return
    if os.path.islink(path):
        os.unlink(path)
    elif os.path.isdir(path):
        shutil.rmtree(path)
    else:
        os.remove(path)
