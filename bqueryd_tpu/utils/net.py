"""Network and packaging utilities (capability match for reference bqueryd/util.py).

``get_my_ip`` avoids the netifaces dependency (not available here) by using a
routing-table probe: a connected UDP socket reveals the address the kernel
would source from, with graceful fallbacks for offline hosts.
"""

import binascii
import os
import random
import socket
import tempfile
import time
import zipfile


def get_my_ip():
    """Best-effort primary IPv4 of this host (reference bqueryd/util.py:13-22
    used netifaces; this uses a UDP routing probe instead — no traffic is sent)."""
    override = os.environ.get("BQUERYD_TPU_IP")
    if override:
        return override
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("10.255.255.255", 1))
            return s.getsockname()[0]
        finally:
            s.close()
    except OSError:
        pass
    try:
        return socket.gethostbyname(socket.gethostname())
    except OSError:
        return "127.0.0.1"


def bind_to_random_port(sock, addr, min_port=49152, max_port=65536, max_tries=100):
    """Bind a ZeroMQ socket to a random tcp port, setting its identity to
    ``<addr>:<port>`` *before* binding (identity must be fixed pre-bind for
    ROUTER-to-ROUTER addressing; same constraint as reference
    bqueryd/util.py:25-41)."""
    import zmq

    for _ in range(max_tries):
        port = random.randrange(min_port, max_port)
        sock.identity = f"{addr}:{port}".encode()
        try:
            sock.bind(f"tcp://*:{port}")
        except zmq.ZMQError as exc:
            if exc.errno == zmq.EADDRINUSE:
                continue
            raise
        return sock.identity.decode()
    raise zmq.ZMQBindError("Could not bind socket to random port.")


def zip_to_file(file_path, destination):
    """Zip a file or directory tree into a temp file under ``destination``;
    returns ``(zip_filename, checksum)`` where the checksum is a CRC over the
    member CRCs (same contract as reference bqueryd/util.py:44-59, used to
    verify shard uploads)."""
    fd, zip_filename = tempfile.mkstemp(suffix=".zip", dir=destination)
    os.close(fd)
    with zipfile.ZipFile(zip_filename, "w", zipfile.ZIP_DEFLATED, allowZip64=True) as zf:
        if os.path.isdir(file_path):
            abs_src = os.path.abspath(file_path)
            for root, _dirs, files in os.walk(file_path):
                for name in files:
                    absname = os.path.abspath(os.path.join(root, name))
                    zf.write(absname, absname[len(abs_src) + 1:])
        else:
            zf.write(file_path, os.path.basename(file_path))
        crc_cat = "".join(str(i.CRC) for i in zf.infolist())
        checksum = hex(binascii.crc32(crc_cat.encode()) & 0xFFFFFFFF)
    return zip_filename, checksum


def tree_checksum(path):
    """CRC over the sorted set of file paths below ``path`` (structure, not
    contents — matches the reference's cheap placement check, reference
    bqueryd/util.py:76-82)."""
    names = set()
    for root, _dirs, files in os.walk(path):
        for name in files:
            names.add(os.path.join(root, name))
    return hex(binascii.crc32("".join(sorted(names)).encode()) & 0xFFFFFFFF)


def show_workers(info_data, only_busy=False):
    """Human-friendly per-node worker listing from an ``rpc.info()`` blob."""
    nodes = {}
    for w in info_data.get("workers", {}).values():
        nodes.setdefault(w.get("node"), []).append(w)
    for node, workers in sorted(nodes.items()):
        print(node)
        for w in workers:
            if only_busy and not w.get("busy"):
                continue
            print("   ", time.ctime(w.get("last_seen", 0)), w.get("workertype"), w.get("busy"))
