"""Per-phase timing and profiler hooks.

The reference's only timing surface is a per-call wall clock on the client
(reference bqueryd/rpc.py:128-129).  The TPU build needs to attribute a query's
latency to its phases — storage decode, host→device transfer, kernel, and
collective merge — so workers attach a :class:`PhaseTimer` to every calc result
(surfaced in the reply under ``phase_timings``) and expose an opt-in
``jax.profiler`` trace hook.
"""

import contextlib
import os
import time


class PhaseTimer:
    """Accumulates named phase durations; phases may recur (times sum)."""

    def __init__(self):
        self.timings = {}
        self._started = time.time()

    @contextlib.contextmanager
    def phase(self, name):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.timings[name] = self.timings.get(name, 0.0) + (
                time.perf_counter() - t0
            )

    def total(self):
        return time.time() - self._started

    def as_dict(self):
        out = dict(self.timings)
        out["total"] = self.total()
        return out


@contextlib.contextmanager
def trace_span(name):
    """A ``jax.profiler.TraceAnnotation`` span when JAX is importable and
    profiling is enabled via BQUERYD_TPU_PROFILE=1; otherwise a no-op."""
    annotation = None
    if os.environ.get("BQUERYD_TPU_PROFILE") == "1":
        try:
            import jax.profiler
        except ImportError:
            pass
        else:
            annotation = jax.profiler.TraceAnnotation(name)
    if annotation is not None:
        with annotation:
            yield
    else:
        yield


@contextlib.contextmanager
def profiler_trace(log_dir):
    """Capture a full ``jax.profiler`` trace (TensorBoard format) around a
    block — the TPU-side replacement for eyeballing ``last_call_duration``."""
    import jax.profiler

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
