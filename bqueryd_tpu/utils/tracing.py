"""Per-phase timing and profiler hooks.

The reference's only timing surface is a per-call wall clock on the client
(reference bqueryd/rpc.py:128-129).  The TPU build needs to attribute a query's
latency to its phases — storage decode, host→device transfer, kernel, and
collective merge — so workers attach a :class:`PhaseTimer` to every calc result
(surfaced in the reply under ``phase_timings``; schema documented in
:mod:`bqueryd_tpu.messages`) and expose an opt-in ``jax.profiler`` trace hook.

A PhaseTimer may carry an :class:`bqueryd_tpu.obs.trace.SpanRecorder`: each
phase then also records a distributed-tracing span (wall-clock start +
perf_counter duration), which is how worker phases reach the controller's
``rpc.trace(trace_id)`` waterfall without a second set of timing call sites.

All durations use ``time.perf_counter`` — including :meth:`PhaseTimer.total`
and the anchor it is measured from.  (``time.time()`` is NOT monotonic: an
NTP step used to make totals negative or smaller than the phase sum.)
"""

import contextlib
import os
import time

#: synthetic key added by :meth:`PhaseTimer.as_dict` — deliberately
#: underscore-namespaced so a real phase named ``total`` can never be
#: silently overwritten (see the reply schema note in messages.py)
TOTAL_KEY = "_total"


class PhaseTimer:
    """Accumulates named phase durations; phases may recur (times sum).

    ``recorder``/``span_names`` (optional): a SpanRecorder receiving one span
    per phase occurrence, names mapped through ``span_names`` (e.g.
    obs.trace.PHASE_SPAN_NAMES' ``open`` -> ``storage_decode``)."""

    def __init__(self, recorder=None, span_names=None):
        import threading

        self.timings = {}
        self.recorder = recorder
        self.span_names = span_names or {}
        self._started = time.perf_counter()
        # phases may now run CONCURRENTLY (the pipelined per-shard engine
        # path times every shard's phases into one timer); the lock keeps
        # the read-modify-write sum from losing updates.  Busy sums of
        # overlapped phases legitimately exceed the wall — that overlap is
        # exactly what bench.py's pipeline section measures.
        self._lock = threading.Lock()

    @contextlib.contextmanager
    def phase(self, name):
        start_ts = time.time()
        t0 = time.perf_counter()
        try:
            yield
        finally:
            duration = time.perf_counter() - t0
            with self._lock:
                self.timings[name] = self.timings.get(name, 0.0) + duration
            if self.recorder is not None:
                self.recorder.record(
                    self.span_names.get(name, name), start_ts, duration
                )

    def debit(self, name, seconds):
        """Subtract a SERIALLY-NESTED sub-phase's wall from its enclosing
        phase (e.g. the device→host ``fetch`` runs inside ``aggregate``):
        without the debit the same seconds bill twice — once per phase —
        in ``phase_timings`` and the per-phase histograms.  The enclosing
        phase's entry may not exist yet (it lands on context exit), so
        this accumulates a negative adjustment that the later sum nets
        out exactly.  Only for nested SERIAL work — genuinely concurrent
        phase overlap is a measured property, never debited."""
        with self._lock:
            self.timings[name] = self.timings.get(name, 0.0) - seconds

    def total(self):
        return time.perf_counter() - self._started

    def as_dict(self):
        out = dict(self.timings)
        out[TOTAL_KEY] = self.total()
        return out


@contextlib.contextmanager
def trace_span(name):
    """A ``jax.profiler.TraceAnnotation`` span when JAX is importable and
    profiling is enabled via BQUERYD_TPU_PROFILE=1; otherwise a no-op.

    When a distributed TraceContext is active (obs.trace contextvar), the
    annotation is tagged with its ``trace_id`` so device profiler timelines
    line up with the RPC trace waterfall."""
    annotation = None
    if os.environ.get("BQUERYD_TPU_PROFILE") == "1":
        try:
            import jax.profiler
        except ImportError:
            pass
        else:
            kwargs = {}
            try:
                from bqueryd_tpu.obs.trace import current_trace

                ctx = current_trace()
                if ctx is not None:
                    kwargs["trace_id"] = ctx.trace_id
            except Exception:
                pass
            annotation = jax.profiler.TraceAnnotation(name, **kwargs)
    if annotation is not None:
        with annotation:
            yield
    else:
        yield


@contextlib.contextmanager
def profiler_trace(log_dir):
    """Capture a full ``jax.profiler`` trace (TensorBoard format) around a
    block — the TPU-side replacement for eyeballing ``last_call_duration``."""
    import jax.profiler

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
