"""Query model + local execution engine (the framework's "flagship model").

A groupby query (the payload of a ``CalcMessage``, same positional contract as
the reference: ``(filename, groupby_col_list, agg_list, where_terms_list)``
with kwargs ``aggregate`` / ``expand_filter_column``, reference
bqueryd/worker.py:277-284) compiles to a pipeline of the kernels in
:mod:`bqueryd_tpu.ops`:

    storage decode -> H2D -> where-mask -> key codes -> packed composite ->
    segment partials -> (mesh psum) -> finalize

Results travel as :class:`ResultPayload`:

* ``kind="partials"``: per-group partial tables **keyed by actual key values**
  (not local codes), so payloads from different workers merge without any
  cross-host dictionary coordination — the host-side merge in
  :mod:`bqueryd_tpu.parallel.hostmerge` aligns them by key.  Mean partials
  carry (sum, count): the correct weighted mean, not the reference's
  sum-of-shard-means (reference bqueryd/rpc.py:171).
* ``kind="rows"``: the ``aggregate=False`` raw-rows path — filtered selected
  columns, concatenated client-side (reference bqueryd/worker.py:316-323,
  rpc.py:172-173).
* ``kind="empty"``: shard pruned by ``shard_can_match`` (the
  factorization-check early-out, reference bqueryd/worker.py:296-301).
"""

import os
import pickle
from dataclasses import dataclass, field

import numpy as np

from bqueryd_tpu.utils import devicehealth

PAYLOAD_FORMAT = "bqueryd-tpu-result-1"

#: the bquery aggregation surface (reference bquery API; reference tests
#: exercise sum/mean/count) plus min/max.  Defined here, JAX-free, so
#: control-plane processes (controller batching decisions) can consult them;
#: bqueryd_tpu.ops re-exports.
AGG_OPS = (
    "sum",
    "mean",
    "count",
    "count_na",
    "count_distinct",
    "sorted_count_distinct",
    "min",
    "max",
)

#: ops whose partials merge with elementwise +/min/max (psum-able); the two
#: distinct-count ops need value sets and take the gather path instead.
MERGEABLE_OPS = ("sum", "mean", "count", "count_na", "min", "max")


def extremum_fill(dtype, kind):
    """Identity fill for per-group ``min``/``max`` partials of ``dtype``:
    'min' fills with the dtype's maximum so any real value wins (and vice
    versa); bool uses its and/or identities, floats +/-inf.  Shared by the
    device kernels, the host kernels, and the cross-payload merge so a new
    dtype special case lives in exactly one place."""
    dtype = np.dtype(dtype)
    if dtype.kind == "f":
        return np.inf if kind == "min" else -np.inf
    if dtype == np.bool_:
        return kind == "min"
    info = np.iinfo(dtype)
    return info.max if kind == "min" else info.min


def normalize_agg_list(agg_list):
    """Agg shorthand normalization: ``"col"`` -> ``[col, 'sum', col]``;
    2-item ``[in, op]`` -> ``[in, op, in]``.  The ONE copy of these rules —
    the worker's :class:`GroupByQuery` and the controller's logical plan
    both use it, so the plan signature (the shared-dispatch fusion key) and
    the executed query can never normalize differently."""
    normalized = []
    for agg in agg_list:
        if isinstance(agg, str):
            normalized.append([agg, "sum", agg])
        elif len(agg) == 2:
            agg = list(agg)
            normalized.append([agg[0], agg[1], agg[0]])
        else:
            normalized.append(list(agg))
    return normalized


def freeze_value(value):
    """Canonical, hashable, collision-free form of a query parameter
    (repr() is ambiguous for numpy arrays, which truncate their repr)."""
    import hashlib

    if isinstance(value, np.ndarray):
        if value.dtype == object:
            # tobytes() of an object array is its POINTER bytes: unstable
            # across (de)serializations and aliasable under allocator
            # reuse — freeze the contained VALUES instead (string
            # dimension-table columns, plan.dag join signatures)
            return ("ndarray-obj", value.shape,
                    tuple(freeze_value(v) for v in value.ravel().tolist()))
        return ("ndarray", value.dtype.str, value.shape,
                hashlib.sha1(value.tobytes()).hexdigest())
    if isinstance(value, (list, tuple)):
        return ("seq", tuple(freeze_value(v) for v in value))
    if isinstance(value, (set, frozenset)):
        return ("set", tuple(sorted((freeze_value(v) for v in value), key=repr)))
    if isinstance(value, np.generic):
        return value.item()
    return value


@dataclass
class GroupByQuery:
    groupby_cols: list
    agg_list: list          # [[in_col, op, out_col], ...]
    where_terms: list = field(default_factory=list)
    aggregate: bool = True
    expand_filter_column: str = None
    #: controller-set hint: this shard's payload is the WHOLE query (single
    #: shard fan-out), so no cross-payload merge will happen — count_distinct
    #: may ship final per-group counts (computed by the device sort kernel)
    #: instead of the distinct value sets an exact cross-shard union needs
    sole_payload: bool = False

    def signature(self):
        """Hashable identity of the query (cache key component)."""
        return (
            tuple(self.groupby_cols),
            freeze_value(self.agg_list),
            freeze_value(self.where_terms or []),
            bool(self.aggregate),
            self.expand_filter_column,
            bool(self.sole_payload),
        )

    def __post_init__(self):
        self.agg_list = normalize_agg_list(self.agg_list)

    @property
    def in_cols(self):
        return [a[0] for a in self.agg_list]

    @property
    def ops(self):
        return tuple(a[1] for a in self.agg_list)

    @property
    def out_cols(self):
        return [a[2] for a in self.agg_list]


def _group_distinct_flat(group_codes, value_codes, value_uniques, n_groups,
                         mask=None):
    """Per-group distinct values in FLAT form: ``(values, offsets)`` where
    group ``g``'s distinct values are ``values[offsets[g]:offsets[g+1]]``.

    The flat form (vs an object array of per-group arrays) keeps the payload
    one contiguous array + one int64 offsets array: cheap to pickle, and the
    cross-shard union merge stays fully vectorized (no per-group Python).

    Null group keys, null values (code < 0, e.g. NaN — matching pandas
    ``nunique(dropna=True)``), and masked-out rows contribute nothing."""
    valid = (group_codes >= 0) & (value_codes >= 0)
    if mask is not None:
        valid &= mask
    nv = max(len(value_uniques), 1)
    pairs = np.unique(
        group_codes[valid].astype(np.int64) * nv + value_codes[valid]
    )
    g_of = pairs // nv
    v_of = pairs % nv
    offsets = np.searchsorted(g_of, np.arange(n_groups + 1)).astype(np.int64)
    return np.asarray(value_uniques)[v_of], offsets


def _segment_local_arange(counts):
    """[0..c0), [0..c1), ... concatenated — index-within-segment helper."""
    total = int(counts.sum())
    if total == 0:
        return np.empty(0, dtype=np.int64)
    starts = np.cumsum(counts) - counts
    return np.arange(total, dtype=np.int64) - np.repeat(starts, counts)


def filter_distinct_part(part, present):
    """Row-filter a flat distinct part to the ``present`` groups."""
    values = part["distinct_values"]
    offsets = part["distinct_offsets"]
    counts = np.diff(offsets)
    sel = counts[present]
    starts = offsets[:-1][present]
    idx = np.repeat(starts, sel) + _segment_local_arange(sel)
    new_offsets = np.zeros(len(sel) + 1, dtype=np.int64)
    np.cumsum(sel, out=new_offsets[1:])
    return {"distinct_values": values[idx], "distinct_offsets": new_offsets}


class ResultPayload(dict):
    """Wire form of a shard/worker result; a plain dict for pickling."""

    @classmethod
    def empty(cls):
        return cls(format=PAYLOAD_FORMAT, kind="empty")

    @classmethod
    def rows(cls, columns, order):
        return cls(format=PAYLOAD_FORMAT, kind="rows", columns=columns, order=order)

    @classmethod
    def partials(cls, key_cols, keys, rows, aggs, ops, out_cols,
                 value_kinds=None):
        return cls(
            format=PAYLOAD_FORMAT,
            kind="partials",
            key_cols=list(key_cols),
            keys=keys,        # {col: np.ndarray[G] of key values}
            rows=rows,        # np.int64[G]
            aggs=aggs,        # list of {partname: np.ndarray[G]}
            ops=list(ops),
            out_cols=list(out_cols),
            # storage kind per agg (None | 'datetime'): partials of datetime
            # measures ride and merge as raw int64; finalize views min/max
            # back to datetime64[ns] (NaT for empty groups)
            value_kinds=(
                [None] * len(list(out_cols))
                if value_kinds is None
                else list(value_kinds)
            ),
        )

    def to_bytes(self):
        return pickle.dumps(dict(self), protocol=4)

    @classmethod
    def from_bytes(cls, buf):
        if not buf:
            return cls.empty()
        obj = pickle.loads(buf)
        if obj.get("format") != PAYLOAD_FORMAT:
            raise ValueError("unknown result payload format")
        return cls(obj)


_measured_floor = None


def device_dispatch_floor(remeasure=False):
    """Measured wall of one trivial jitted dispatch + host fetch on the
    default backend (min of 3, cached per process).  On a remote/tunneled
    device this is tens of ms of pure transport; on local hardware,
    microseconds.  The fetch is included because the device query path ends
    in a ``device_get`` — that is the cost host routing competes against.

    A measurement taken while another thread holds the backend (e.g. the
    worker's background warmup compile) is inflated; the warmup thread
    calls ``remeasure=True`` when it finishes to replace any such sample."""
    global _measured_floor
    if devicehealth.backend_wedged():
        # do NOT cache: a recovered backend must remeasure a real floor
        return devicehealth.probe_timeout_s()
    if _measured_floor is None or remeasure:
        import time

        def _measure():
            import jax
            import jax.numpy as jnp
            import numpy as np

            f = jax.jit(lambda x: x + 1)
            np.asarray(f(jnp.zeros(())))
            walls = []
            for _ in range(3):
                t0 = time.perf_counter()
                np.asarray(f(jnp.zeros(())))
                walls.append(time.perf_counter() - t0)
            return min(walls)

        # the measurement IS a device dispatch: on a wedged backend it
        # would hang the calling thread (historically the worker loop, via
        # the first query's routing) forever.  Run it sacrificially; a
        # deadline miss latches the backend and host routing takes over.
        timeout = devicehealth.probe_timeout_s()
        if timeout <= 0:  # detection disabled: measure directly
            _measured_floor = _measure()
            return _measured_floor
        done, floor = devicehealth.run_with_deadline(_measure, timeout)
        if not done or floor is None:
            devicehealth.latch_wedged()
            return devicehealth.probe_timeout_s()
        _measured_floor = floor
    return _measured_floor


#: assumed host aggregation cost per row (cached codes + fast-path
#: bincounts: ~7ns/row measured at 1M rows x 9 groups, rounded up),
#: used only to convert the measured dispatch floor into a row threshold
_HOST_NS_PER_ROW = 8e-9

#: cost when a measure misses the fast paths: the 16-bit-limb exact int
#: sum (4 weighted bincounts) or np.minimum/maximum.at extrema run ~4x
#: the fast-path rate, so near-threshold queries must not be host-routed
#: on the optimistic estimate
_HOST_NS_PER_ROW_SLOW = 32e-9


def _host_ns_estimate(table, agg_list, n_rows):
    """Per-row host-kernel cost for routing, from column METADATA only
    (physical dtype + chunk min/max stats — no decode): integer sums whose
    ``n x max|value|`` bound stays under 2^53 take the single-bincount
    fast path; larger-magnitude (or stats-less) int sums and min/max pay
    the slow rate."""
    from bqueryd_tpu.ops.groupby import (
        _NATIVE_GROUPBY_MIN_ROWS,
        HOST_EXACT_SUM_BOUND,
    )

    native_ok = None  # computed lazily: import + symbol probe

    def native_takes_it():
        # The C++ kernels sum in uint64 (exact at any magnitude) and do
        # min/max in one striped pass, so queries they will take have no
        # slow fallback to price in.  (They decline above their group
        # ceiling — unknown until factorize — in which case the numpy
        # path runs mis-rated; high-cardinality host routes are rare
        # enough to accept that.)
        nonlocal native_ok
        if native_ok is None:
            from bqueryd_tpu.storage import native

            native_ok = (
                n_rows >= _NATIVE_GROUPBY_MIN_ROWS
                and native.groupby_available()
            )
        return native_ok

    minmax_ok = None

    def _native_minmax_ok():
        nonlocal minmax_ok
        if minmax_ok is None:
            from bqueryd_tpu.storage import native

            minmax_ok = native.groupby_minmax_available()
        return minmax_ok

    for in_col, op, _out in agg_list:
        if op in ("min", "max"):
            # extrema need the dedicated min/max kernel, which also
            # declines unsigned dtypes (uint64 would wrap its signed i64
            # accumulator) — those queries run numpy ufunc.at, the slow rate
            if (
                table.kind(in_col) == "datetime"
                or not native_takes_it()
                or not _native_minmax_ok()
                or np.issubdtype(
                    table.physical_dtype(in_col), np.unsignedinteger
                )
            ):
                return _HOST_NS_PER_ROW_SLOW  # numpy ufunc.at extrema
            continue
        if op in ("sum", "mean") and np.issubdtype(
            table.physical_dtype(in_col), np.integer
        ):
            if native_takes_it():
                continue
            stats = table.col_stats(in_col)
            if stats is None:
                return _HOST_NS_PER_ROW_SLOW
            bound = max(abs(int(stats[0])), abs(int(stats[1])))
            if bound * max(int(n_rows), 1) >= HOST_EXACT_SUM_BOUND:
                return _HOST_NS_PER_ROW_SLOW
    return _HOST_NS_PER_ROW

#: never host-route queries above this many rows, however slow the device
#: link — large queries belong on the device program.  (A blanket
#: host-route-everything rule for CPU backends was tried and measured WORSE:
#: numpy wins on few-group sums but XLA's scatter wins at high cardinality,
#: so the latency-derived threshold below is the rule on every backend.)
_HOST_ROUTE_CAP = 4_000_000

#: multi-key composite spaces at most this large aggregate directly over the
#: full (K1*...*Kn)-slot space instead of paying an O(n) compaction pass;
#: empty combos are dropped at collect, so only kernel minlength grows
_DENSE_COMBO_CAP = 1 << 16


def host_kernel_rows(ns_per_row=None):
    """Row threshold below which mergeable aggregations run on the HOST
    (:func:`ops.host_partial_tables`) instead of paying a device round-trip.

    Latency-aware routing: when the device sits behind a network tunnel the
    dispatch+fetch floor dwarfs the kernel for small inputs, so the host is
    strictly faster; on local chips the measured floor is microseconds and
    the threshold collapses to ~10k rows.  ``ns_per_row`` lets the caller
    pass a per-query cost estimate (:func:`_host_ns_estimate`); default is
    the fast-path rate.  Override with BQUERYD_TPU_HOST_KERNEL_ROWS
    (0 disables host routing)."""
    if devicehealth.backend_wedged(launch=False):
        # wedged backend: EVERY query the host kernels can serve must go
        # host — the alternative is a worker loop hung inside native code.
        # Deliberately overrides the env pin (an operator's device-only
        # setting is about performance; a wedge is about survival) and the
        # 4M-row cap (the cap encodes host-vs-device economics that do not
        # exist while the device cannot answer at all).
        return 1 << 62
    env = os.environ.get("BQUERYD_TPU_HOST_KERNEL_ROWS")
    if env is not None:
        try:
            return max(int(env), 0)
        except ValueError:
            import logging

            logging.getLogger("bqueryd_tpu").warning(
                "unparseable BQUERYD_TPU_HOST_KERNEL_ROWS=%r, "
                "host routing disabled", env,
            )
            return 0
    ns = _HOST_NS_PER_ROW if ns_per_row is None else ns_per_row
    return min(int(device_dispatch_floor() / ns), _HOST_ROUTE_CAP)


def _value_kind_for(table, col):
    """Storage-kind tag carried per agg in the payload: 'datetime' restores
    datetime64 at finalize; 'uint64' re-views mod-2^64 sums as unsigned
    (every kernel path accumulates the same bits either way — only the
    presentation differs, matching pandas' uint64 groupby sums); 'uint'
    marks narrower unsigned storage so a cross-shard merge can tell a
    narrow unsigned sibling of a uint64 shard (reconcile to the unsigned
    view) from a signed/float sibling (refuse: reinterpreting a widened
    signed or float total as uint64 would corrupt it)."""
    if table.kind(col) == "datetime":
        return "datetime"
    dt = table.physical_dtype(col)
    if dt == np.dtype(np.uint64):
        return "uint64"
    if dt.kind == "u":
        return "uint"
    return None


class QueryEngine:
    """Executes queries against local tpucolz tables on the local JAX device
    (single-device path; the multi-device mesh path lives in
    bqueryd_tpu.parallel.executor).  JAX imports happen lazily on first use so
    control-plane processes can import this module freely."""

    def __init__(self, timer=None):
        self.timer = timer
        #: the physical kernel route of the last execute_local (post-guards;
        #: "host" for host-routed queries) — surfaced by the worker as
        #: ``effective_strategy`` in calc replies and kernel trace spans
        self.last_effective_strategy = None
        from bqueryd_tpu.utils.cache import BytesCappedCache

        # per-(table, column) factorization cache: the host analogue of
        # bquery's on-disk factorize cache (reference bqueryd/worker.py:291,
        # auto_cache=True) — repeated queries on unchanged shards skip the
        # hash factorize entirely.  Keyed on the shard's meta identity, so
        # activation invalidates naturally.
        self._factorize_cache = BytesCappedCache(
            int(
                os.environ.get(
                    "BQUERYD_TPU_FACTORIZE_CACHE_BYTES", 256 * 1024**2
                )
            )
        )

    def clear_caches(self):
        """Drop the factorize cache (memory-watchdog hook)."""
        self._factorize_cache.clear()

    def _phase(self, name):
        import contextlib

        if self.timer is None:
            return contextlib.nullcontext()
        return self.timer.phase(name)

    # -- key handling ------------------------------------------------------
    def _key_codes(self, table, col, mask_np=None):
        """Physical dense codes + key-value array for one groupby column."""
        from bqueryd_tpu import ops

        kind = table.kind(col)
        if kind == "dict":
            codes = table.column_raw(col)
            values = np.asarray(table.dictionary(col), dtype=object)
            return codes, values
        from bqueryd_tpu.storage.ctable import table_cache_key

        cache_key = (table_cache_key(table), col)
        hit = self._factorize_cache.get(cache_key)
        if hit is not None:
            return hit
        # disk sidecar (bquery's auto_cache analogue, stored POST null-poison
        # so a load skips the NaN/NaT scan too) before paying the
        # decode+factorize
        loader = getattr(table, "factor_cache_load", None)
        if loader is not None:
            disk = loader(col)
            if disk is not None:
                codes, uniques = disk
                if kind == "datetime" and uniques.dtype.kind != "M":
                    uniques = uniques.view("datetime64[ns]")
                self._factorize_cache.put(
                    cache_key, (codes, uniques),
                    nbytes=codes.nbytes + uniques.nbytes,
                )
                return codes, uniques
        # stamp BEFORE the read: if the shard is rewritten mid-factorize the
        # sidecar lands stale (future miss), never poisoned (see the TOCTOU
        # note in storage/ctable.py)
        stamper = getattr(table, "factor_stamp", None)
        stamp = stamper(col) if stamper is not None else None
        raw = table.column_raw(col)
        codes, uniques = ops.factorize(raw)
        if kind == "datetime":
            uniques = uniques.view("datetime64[ns]")
        # NaN/NaT uniques are nulls, not values: poison their codes to -1 so
        # those rows drop from group keys (pandas dropna) and from distinct
        # sets (pandas nunique skips nulls) — ops.factorize itself treats
        # them as ordinary keys and documents that callers pre-filter
        null_at = None
        if kind == "datetime":
            null_at = np.flatnonzero(np.isnat(uniques))
        elif np.issubdtype(np.asarray(uniques).dtype, np.floating):
            null_at = np.flatnonzero(np.isnan(uniques))
        if null_at is not None and len(null_at):
            codes = np.where(
                np.isin(codes, null_at), np.int64(-1), codes
            )
        storer = getattr(table, "factor_cache_store", None)
        if storer is not None and stamp is not None:
            storer(col, codes, uniques, stamp=stamp)
        self._factorize_cache.put(
            cache_key, (codes, uniques), nbytes=codes.nbytes + uniques.nbytes
        )
        return codes, uniques

    def _basket_codes(self, table, col):
        """Basket-expansion codes for ``expand_filter_column`` — cached like
        :meth:`_key_codes` but with the basket semantics the engine always
        shipped: the factorize runs over the PHYSICAL column, so dict-encoded
        nulls (code -1) become one ordinary, selectable basket group (the
        basket key is a plain value column, matching the reference's
        ``is_in_ordered_subgroups`` which knows nothing about nulls)."""
        from bqueryd_tpu import ops
        from bqueryd_tpu.storage.ctable import table_cache_key

        cache_key = (table_cache_key(table), col, "basket")
        hit = self._factorize_cache.get(cache_key)
        if hit is not None:
            return hit
        codes, uniques = ops.factorize(np.asarray(table.column_raw(col)))
        self._factorize_cache.put(
            cache_key, (codes, uniques), nbytes=codes.nbytes + uniques.nbytes
        )
        return codes, uniques

    # -- execution ---------------------------------------------------------
    def execute_local(self, table, query: GroupByQuery,
                      strategy=None) -> ResultPayload:
        """``strategy`` is the planner's kernel-route hint: ``"host"`` forces
        the NumPy kernels (bypassing the latency threshold), ``"scatter"`` /
        ``"sort"`` / ``"matmul"`` flow into :func:`ops.partial_tables` (the
        matmul hint stays advisory there); None/"auto" keeps the adaptive
        default.  A wedged backend overrides every device hint — survival
        beats planning."""
        from bqueryd_tpu import ops

        self.last_effective_strategy = None  # set by the kernel dispatch
        if query.aggregate:
            # reject pandas-meaningless datetime sums/means before any
            # decode/factorize work is spent on the query
            for in_col, op in zip(query.in_cols, query.ops):
                if op in ("sum", "mean") and table.kind(in_col) == "datetime":
                    raise ValueError(
                        f"{op!r} is not defined for datetime "
                        f"column {in_col!r}"
                    )

        with self._phase("prune"):
            if query.where_terms and not ops.shard_can_match(
                table, query.where_terms
            ):
                return ResultPayload.empty()

        with self._phase("mask"):
            mask = ops.build_mask(table, query.where_terms)
            if query.expand_filter_column:
                basket_codes, basket_uniques = self._basket_codes(
                    table, query.expand_filter_column
                )
                mask = ops.expand_mask_by_group(
                    basket_codes, mask, n_groups=len(basket_uniques)
                )

        if not query.aggregate:
            return self._raw_rows(table, query, mask)

        with self._phase("factorize"):
            per_key = [self._key_codes(table, c) for c in query.groupby_cols]
            code_arrays = [np.asarray(c) for c, _ in per_key]
            key_values = [v for _, v in per_key]
            cards = [len(v) for v in key_values]
            combo_cols = None  # set by the CompositeOverflow fallback only
            # Null keys (code -1, dict-encoded missing values) stay -1 in the
            # dense codes: every kernel treats negative codes as invalid, so
            # null-key rows vanish from the aggregation (pandas dropna
            # semantics, same convention as the mesh executor's alignment).
            # Re-factorizing them into a real group would make ``collect``
            # index key_values[-1] — a wrapped, wrong key.
            if len(code_arrays) == 1:
                # _key_codes already produced dense first-seen codes into
                # key_values, so a second factorize is the identity map —
                # skipping it saves ~12ms/M rows, the whole host-route budget
                dense = code_arrays[0]
                combos = np.arange(cards[0], dtype=np.int64)
                n_groups = max(cards[0], 1)
            elif ops.total_cardinality(cards) >= ops.MAX_COMPOSITE:
                # radix packing would wrap (CompositeOverflow): factorize
                # the key TUPLES instead.  O(n log n) via a void-record
                # unique, null rows (any component -1) poisoned up front.
                # combos are not radix-decodable here, so the per-column
                # codes of each combo ride along for collect.
                stacked = np.stack(
                    [np.asarray(c, dtype=np.int64) for c in code_arrays],
                    axis=1,
                )
                valid = (stacked >= 0).all(axis=1)
                view = np.ascontiguousarray(stacked[valid]).view(
                    [("", np.int64)] * stacked.shape[1]
                ).ravel()
                uniq, inv = np.unique(view, return_inverse=True)
                dense = np.full(len(stacked), np.int64(-1))
                dense[valid] = inv
                combo_cols = (
                    uniq.view(np.int64).reshape(len(uniq), stacked.shape[1])
                )
                combos = np.arange(len(uniq), dtype=np.int64)
                n_groups = max(len(uniq), 1)
            else:
                packed = ops.pack_codes(code_arrays, cards)
                total_card = ops.total_cardinality(cards)
                if total_card <= _DENSE_COMBO_CAP:
                    # composite space small enough to aggregate over
                    # directly; empty combos drop at collect via rows == 0
                    dense = packed
                    combos = np.arange(total_card, dtype=np.int64)
                    n_groups = max(total_card, 1)
                else:
                    # compact the sparse composite space with the O(n) hash
                    # factorizer, then evict the null composite (-1) from
                    # the group dictionary so it stays invalid downstream.
                    # (Unsorted first-seen combos are fine here: hostmerge
                    # aligns payloads by key VALUES, unlike the mesh
                    # executor's alignment which needs a sorted global
                    # order.)
                    dense, combos = ops.factorize(packed)
                    null_at = np.flatnonzero(combos == -1)
                    if len(null_at):
                        j = int(null_at[0])
                        remap = np.empty(len(combos), dtype=np.int64)
                        remap[:j] = np.arange(j)
                        remap[j] = -1
                        remap[j + 1:] = np.arange(j, len(combos) - 1)
                        dense = remap[dense]
                        combos = np.delete(combos, j)
                    n_groups = max(len(combos), 1)

        with self._phase("aggregate"):
            mask_arr = None if mask is None else np.asarray(mask)
            mergeable = [
                (i, a) for i, a in enumerate(query.agg_list)
                if a[1] in ops.MERGEABLE_OPS
            ]
            distinct = [
                (i, a) for i, a in enumerate(query.agg_list)
                if a[1] not in ops.MERGEABLE_OPS
            ]
            agg_parts = [None] * len(query.agg_list)
            if mergeable:
                measures = tuple(
                    table.column_raw(a[0]) for _, a in mergeable
                )
                mops = tuple(a[1] for _, a in mergeable)
                # datetime measures: NaT (int64 min) is a null sentinel so
                # those rows skip counts/extrema like float NaNs (pandas);
                # datetime sums/means were rejected on entry
                sentinels = tuple(
                    np.iinfo(np.int64).min
                    if table.kind(a[0]) == "datetime"
                    else None
                    for _, a in mergeable
                )
                if strategy == "host" or len(dense) <= host_kernel_rows(
                    _host_ns_estimate(
                        table, [a for _, a in mergeable], len(dense)
                    )
                ):
                    # latency-aware routing: below the threshold the host
                    # beats the device's dispatch+fetch floor (see
                    # host_kernel_rows); identical partial semantics.  The
                    # planner's "host" hint forces this branch outright.
                    import time as _time

                    from bqueryd_tpu.plan import calibrate as _calibrate

                    self.last_effective_strategy = "host"
                    host_clock = _time.perf_counter()
                    partials = ops.host_partial_tables(
                        dense.astype(np.int32), measures, mops, n_groups,
                        mask_arr, null_sentinels=sentinels,
                    )
                    # host walls are calibration data points too (no
                    # compile taint to filter on this route)
                    _calibrate.record_sample(
                        rows=len(dense), groups=n_groups,
                        dtypes=[np.asarray(m).dtype for m in measures],
                        backend="host", strategy="host",
                        wall_s=_time.perf_counter() - host_clock,
                    )
                else:
                    import time as _time

                    import jax

                    from bqueryd_tpu.obs import profile as _obs_profile
                    from bqueryd_tpu.plan import calibrate as _calibrate

                    # bucketed group count (ops.program_bucket): program
                    # reuse across cardinality drift; padded groups are
                    # zero-row and sliced off after the fetch
                    n_prog = ops.program_bucket(n_groups)
                    kernel_strategy = (
                        strategy
                        if strategy in ("matmul", "scatter", "sort",
                                        "matmul!")
                        else None
                    )
                    np_measures = [np.asarray(m) for m in measures]
                    route = ops.kernel_route(
                        kernel_strategy, np_measures, mops,
                        len(dense), n_prog,
                    )
                    self.last_effective_strategy = route
                    profiler = _obs_profile.profiler()
                    misses_before = profiler.jit_cache_misses
                    kernel_clock = _time.perf_counter()
                    partials = jax.device_get(  # ONE batched D2H round-trip
                        ops.partial_tables(
                            dense.astype(np.int32), measures, mops, n_prog,
                            mask_arr, null_sentinels=sentinels,
                            strategy=kernel_strategy,
                        )
                    )
                    # measured-cost calibration sample (plan.calibrate);
                    # compile-tainted walls are skipped — a first-shape
                    # compile would poison the route's EWMA
                    if (
                        _calibrate.enabled()
                        and profiler.jit_cache_misses == misses_before
                    ):
                        _calibrate.record_sample(
                            rows=len(dense), groups=n_groups,
                            dtypes=[m.dtype for m in np_measures],
                            backend=jax.default_backend(),
                            strategy=route,
                            wall_s=_time.perf_counter() - kernel_clock,
                        )
                    if n_prog != n_groups:
                        partials = jax.tree_util.tree_map(
                            lambda a: a[:n_groups], partials
                        )
                rows = partials["rows"]
                for (i, _a), part in zip(mergeable, partials["aggs"]):
                    agg_parts[i] = dict(part)
            else:
                # rows still needed to drop empty groups
                if devicehealth.backend_wedged():
                    # the numpy twin shares partial_tables' exact row
                    # semantics (negative codes dropped, mask applied)
                    rows = np.asarray(
                        ops.host_partial_tables(
                            dense.astype(np.int32), (), (), n_groups,
                            mask_arr,
                        )["rows"]
                    )[:n_groups]
                else:
                    rows = np.asarray(
                        ops.partial_tables(
                            dense.astype(np.int32),
                            (np.zeros(len(dense)),),
                            ("count",),
                            ops.program_bucket(n_groups),
                            mask_arr,
                        )["rows"]
                    )[:n_groups]
            for i, agg in distinct:
                in_col, op, _out = agg
                vals = table.column_raw(in_col)
                counts = None
                if (
                    op == "count_distinct"
                    and query.sole_payload
                    # wedged backend: fall through to the host set-shipping
                    # branch below instead of hanging on the device sort
                    and not devicehealth.backend_wedged()
                ):
                    # single-shard query: this payload IS the final result,
                    # so the device sort kernel's per-group counts suffice
                    # (a device radix sort beats host np.unique at scale)
                    vcodes, vuniques = self._key_codes(table, in_col)
                    try:
                        counts = ops.groupby_count_distinct(
                            dense.astype(np.int32),
                            np.asarray(vcodes),
                            ops.program_bucket(n_groups),
                            # bucketing n_values keeps the composite
                            # mapping injective (codes < actual < bucket),
                            # so distinct counts are unchanged while the
                            # program shape survives cardinality drift
                            ops.program_bucket(max(len(vuniques), 1)),
                            mask_arr,
                        )
                    except ops.CompositeOverflow:
                        # (group, value) space past int64: the set-shipping
                        # branch below answers exactly without packing
                        pass
                if counts is not None:
                    agg_parts[i] = {
                        "distinct": np.asarray(counts)[:n_groups]
                    }
                elif op == "count_distinct":
                    # ship the per-group distinct VALUE SETS, not counts:
                    # sets union exactly across shards/workers, where the
                    # reference's forced-'sum' client merge double-counts
                    # values that span shards (reference bqueryd/rpc.py:171).
                    # _key_codes resolves dict-encoded and datetime columns
                    # to their actual values — per-shard dictionary codes
                    # live in incompatible code spaces and must never cross
                    # a shard boundary raw.
                    vcodes, vuniques = self._key_codes(table, in_col)
                    values, offsets = _group_distinct_flat(
                        np.asarray(dense), np.asarray(vcodes),
                        np.asarray(vuniques), n_groups, mask_arr,
                    )
                    # exact cross-shard merge requires shipping the sets, so
                    # payload size grows with total distinct values (worst
                    # case ~ the whole column); a configurable cap keeps a
                    # pathological query from exhausting worker/client memory
                    limit = int(os.environ.get(
                        "BQUERYD_TPU_DISTINCT_VALUES_LIMIT", 5_000_000
                    ))
                    if limit and len(values) > limit:
                        raise ValueError(
                            f"count_distinct on {in_col!r}: {len(values)} "
                            f"(group, value) pairs exceeds the payload cap "
                            f"{limit}; raise "
                            f"BQUERYD_TPU_DISTINCT_VALUES_LIMIT to allow"
                        )
                    agg_parts[i] = {
                        "distinct_values": values,
                        "distinct_offsets": offsets,
                    }
                elif op == "sorted_count_distinct":
                    # run-boundary counts are inherently per-shard (the sort
                    # order is local); cross-shard merge stays additive
                    if devicehealth.backend_wedged():
                        # numpy twin with identical run-leader semantics:
                        # the last device-only op also survives a wedge
                        counts = ops.host_sorted_count_distinct(
                            dense.astype(np.int32), vals,
                            n_groups, mask_arr,
                        )
                    else:
                        counts = ops.groupby_sorted_count_distinct(
                            dense.astype(np.int32), vals,
                            ops.program_bucket(n_groups), mask_arr,
                        )
                    agg_parts[i] = {
                        "distinct": np.asarray(counts)[:n_groups]
                    }
                else:
                    raise ValueError(f"unknown aggregation op {op!r}")

        with self._phase("collect"):
            present = rows > 0
            combos_present = combos[present]
            keys = {}
            if len(query.groupby_cols) == 1:
                key_codes = [combos_present]
            elif combo_cols is not None:
                # tuple-factorized combos (CompositeOverflow fallback):
                # per-column codes were kept alongside, not radix-packed
                key_codes = [
                    combo_cols[np.asarray(combos_present), ci]
                    for ci in range(combo_cols.shape[1])
                ]
            else:
                from bqueryd_tpu import ops as _ops

                key_codes = _ops.unpack_codes(combos_present, cards)
            for col, codes_g, values in zip(
                query.groupby_cols, key_codes, key_values
            ):
                idx = np.asarray(codes_g, dtype=np.int64)
                keys[col] = np.asarray(values)[idx]
            aggs = [
                filter_distinct_part(part, present)
                if "distinct_offsets" in part
                else {k: v[present] for k, v in part.items()}
                for part in agg_parts
            ]
            return ResultPayload.partials(
                key_cols=query.groupby_cols,
                keys=keys,
                rows=np.asarray(rows)[present],
                aggs=aggs,
                ops=query.ops,
                out_cols=query.out_cols,
                value_kinds=[_value_kind_for(table, a[0])
                             for a in query.agg_list],
            )

    def _raw_rows(self, table, query, mask):
        column_list = list(query.groupby_cols) + list(query.in_cols)
        seen = set()
        column_list = [c for c in column_list if not (c in seen or seen.add(c))]
        idx = None if mask is None else np.flatnonzero(np.asarray(mask))
        columns = {}
        for col in column_list:
            values = table.column(col)
            columns[col] = values if idx is None else values[idx]
        return ResultPayload.rows(columns, column_list)
