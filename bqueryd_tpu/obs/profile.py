"""Compile & device profiling: the two TPU costs PR 2's tracing can't see.

On a tunneled TPU backend the wall that dominates a cold query is XLA
compilation (20-40 s per program shape, ``ops/__init__.py``'s persistent
cache notwithstanding), and the resource that silently kills a hot one is
device memory.  Neither shows up in span waterfalls: a compile hides inside
the first ``kernel`` span of its shape, and HBM pressure shows up only as an
eventual RESOURCE_EXHAUSTED.  This module makes both first-class:

* :func:`instrument` wraps a jitted entry point (``ops/groupby.py``'s
  partial-table programs, ``parallel/executor.py``'s mesh program).  Every
  top-level call is accounted against the jit cache (`hit` when the traced
  program was reused, `miss` when the call compiled — detected by cache-size
  growth), compile walls land in a fixed-bucket histogram, and each new
  program shape gets a registry entry carrying ``lower().cost_analysis()``
  FLOPs / bytes-accessed (host-side HLO cost analysis — deliberately NOT
  ``lower().compile().cost_analysis()``, which would pay a second 20-40 s
  backend compile per shape on a tunneled backend for the same numbers).
* persistent-compile-cache hits/misses are counted via ``jax.monitoring``
  event listeners (the channel ``jax._src.compiler`` reports on), so the
  fleet-warming story of the disk cache is measurable, not assumed.
* :meth:`ProgramProfiler.bind` exposes it all on a node's
  :class:`~bqueryd_tpu.obs.metrics.MetricsRegistry`, including HBM-watermark
  gauges sampled from ``device.memory_stats()`` — read at scrape time from
  devices cached AFTER a successful kernel call, so a metrics scrape can
  never be the thing that first touches (and hangs on) a dead tunnel.

The profiler is process-global (one XLA backend, one persistent cache per
process), unlike the per-node registries: in-process test clusters share it,
which :meth:`MetricsRegistry.register` makes explicit by adopting the same
metric instances into several registries.

Control-plane module at import time: stdlib only; JAX is imported lazily
inside the call paths that only jax-owning processes reach.
"""

import os
import threading
import time

from bqueryd_tpu.obs import metrics as metrics_mod

#: registry entries kept; least-recently-called evicted past this
MAX_PROGRAMS = 256

#: jax.monitoring event names for the persistent compilation cache
_PERSISTENT_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_PERSISTENT_MISS_EVENT = "/jax/compilation_cache/cache_misses"


def profiling_enabled():
    """Compile profiling on/off (read per call: live-tunable).  Rides the
    same hot path as span recording, so ``BQUERYD_TPU_METRICS=0`` disables
    it too (checked by the caller via ``obs.enabled()``)."""
    return os.environ.get("BQUERYD_TPU_COMPILE_PROFILE", "1") != "0"


def cost_analysis_enabled():
    """Whether a compile event also runs host-side HLO cost analysis (one
    re-trace + lowering per NEW shape — milliseconds, but gated anyway)."""
    return os.environ.get("BQUERYD_TPU_COST_ANALYSIS", "1") != "0"


def _trace_clean():
    """False while under a jax trace: an instrumented inner program (e.g.
    ``partial_tables`` inlined into the mesh program's shard_map body) must
    pass straight through — tracer args, no real dispatch to account."""
    try:
        import jax.core

        return jax.core.trace_state_clean()
    except Exception:
        return True


def _shape_signature(name, args, kwargs):
    """Stable per-shape key: abstract (dtype[shape]) per array leaf, repr for
    static values — what the jit cache itself keys on, human-readable."""
    import jax

    parts = []
    for leaf in jax.tree_util.tree_leaves((args, kwargs)):
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            dims = ",".join(str(d) for d in leaf.shape)
            parts.append(f"{leaf.dtype}[{dims}]")
        else:
            parts.append(repr(leaf)[:48])
    return f"{name}({';'.join(parts)})"


class ProgramProfiler:
    """Process-wide compile/device profile state (see module docstring)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.compile_seconds = metrics_mod.Histogram(
            "bqueryd_tpu_compile_seconds",
            "wall of jitted calls that compiled a new program "
            "(compile + first run)",
        )
        self.jit_cache_hits = 0
        self.jit_cache_misses = 0
        self.persistent_cache_hits = 0
        self.persistent_cache_misses = 0
        self.programs = {}        # signature -> registry entry dict
        self.programs_evicted = 0
        self._call_seq = 0        # recency order for eviction/snapshot
                                  # (wall timestamps tie at sub-ms cadence)
        self._monitoring_hooked = False
        self._devices = None      # cached jax.local_devices(), post-success

    # -- jax.monitoring bridge ----------------------------------------------
    def _ensure_monitoring(self):
        """Register persistent-cache listeners once per process.  Lazy (on
        the first compile event) so jax-free processes never import jax."""
        if self._monitoring_hooked:
            return
        self._monitoring_hooked = True
        try:
            import jax.monitoring

            def _event(event, *args, **kwargs):
                if event == _PERSISTENT_HIT_EVENT:
                    with self._lock:
                        self.persistent_cache_hits += 1
                elif event == _PERSISTENT_MISS_EVENT:
                    with self._lock:
                        self.persistent_cache_misses += 1

            jax.monitoring.register_event_listener(_event)
        except Exception:
            pass  # old jax without monitoring: counters just stay 0

    # -- per-call accounting -------------------------------------------------
    def record_call(self, name, jitted, args, kwargs, compiled, duration_s,
                    signature=None):
        if signature is None:
            signature = _shape_signature(name, args, kwargs)
        cost = None
        if compiled:
            self._ensure_monitoring()
            self.compile_seconds.observe(duration_s)
            cost = self._cost_analysis(jitted, args, kwargs)
        now = time.time()
        with self._lock:
            if compiled:
                self.jit_cache_misses += 1
            else:
                self.jit_cache_hits += 1
            entry = self.programs.get(signature)
            if entry is None:
                entry = self.programs[signature] = {
                    "name": name,
                    "signature": signature,
                    "calls": 0,
                    "compiles": 0,
                    "jit_cache_hits": 0,
                    "total_compile_s": 0.0,
                    "last_compile_s": None,
                    "flops": None,
                    "bytes_accessed": None,
                    "first_ts": round(now, 3),
                    # stamped before the eviction scan below: a new entry
                    # missing its recency marker would min() as the oldest
                    # and evict ITSELF, freezing the registry at the first
                    # MAX_PROGRAMS shapes ever seen
                    "_seq": self._call_seq + 1,
                }
                while len(self.programs) > MAX_PROGRAMS:
                    oldest = min(
                        self.programs.values(),
                        key=lambda e: e.get("_seq", 0),
                    )
                    self.programs.pop(oldest["signature"], None)
                    self.programs_evicted += 1
            self._call_seq += 1
            entry["calls"] += 1
            entry["_seq"] = self._call_seq
            entry["last_call_ts"] = round(now, 3)
            if compiled:
                entry["compiles"] += 1
                entry["last_compile_s"] = round(duration_s, 4)
                entry["total_compile_s"] = round(
                    entry["total_compile_s"] + duration_s, 4
                )
                if cost:
                    entry.update(cost)
            else:
                entry["jit_cache_hits"] += 1

    @staticmethod
    def _cost_analysis(jitted, args, kwargs):
        """FLOPs / bytes for one program shape via host-side HLO cost
        analysis on the re-traced lowering (no backend compile)."""
        if not cost_analysis_enabled():
            return None
        try:
            cost = jitted.lower(*args, **kwargs).cost_analysis()
            if isinstance(cost, (list, tuple)):
                cost = cost[0] if cost else {}
            return {
                "flops": float(cost.get("flops", 0.0)),
                "bytes_accessed": float(cost.get("bytes accessed", 0.0)),
            }
        except Exception:
            return None

    # -- device memory -------------------------------------------------------
    def note_devices(self):
        """Cache the local device list AFTER a successful kernel call — the
        only moment it is provably safe to enumerate devices without risking
        a first backend touch that hangs on a dead tunnel."""
        if self._devices is None:
            try:
                import jax

                self._devices = list(jax.local_devices())
            except Exception:
                pass

    def device_memory(self):
        """Per-device ``memory_stats()`` snapshots (may be empty: backend
        not yet proven alive, or a backend without stats, e.g. CPU)."""
        out = []
        for i, dev in enumerate(self._devices or ()):
            try:
                stats = dev.memory_stats()
            except Exception:
                stats = None
            if stats:
                out.append(
                    {
                        "device": i,
                        "kind": getattr(dev, "device_kind", "?"),
                        "bytes_in_use": stats.get("bytes_in_use"),
                        "peak_bytes_in_use": stats.get("peak_bytes_in_use"),
                        "bytes_limit": stats.get("bytes_limit"),
                    }
                )
        return out

    def memory_sample(self):
        """Fleet-of-local-devices summary for gauges and span attribution:
        ``{"bytes_in_use": sum, "peak_bytes_in_use": max, "bytes_limit":
        sum}`` — or None when no device reports stats."""
        per_device = self.device_memory()
        if not per_device:
            return None
        return {
            "bytes_in_use": sum(d["bytes_in_use"] or 0 for d in per_device),
            "peak_bytes_in_use": max(
                d["peak_bytes_in_use"] or 0 for d in per_device
            ),
            "bytes_limit": sum(d["bytes_limit"] or 0 for d in per_device),
        }

    def _memory_gauge(self, key):
        def read():
            sample = self.memory_sample()
            return float("nan") if sample is None else float(sample[key] or 0)

        return read

    def last_program(self, name_prefix):
        """The most recently CALLED registry entry whose ``name`` starts
        with ``name_prefix`` (a copy), or None — how the calibration layer
        attaches a program's cost_analysis FLOPs/bytes to the kernel walls
        it records (plan.calibrate)."""
        with self._lock:
            best = None
            for entry in self.programs.values():
                if not str(entry.get("name", "")).startswith(name_prefix):
                    continue
                if best is None or entry.get("_seq", 0) > best.get("_seq", 0):
                    best = entry
            return dict(best) if best is not None else None

    # -- export --------------------------------------------------------------
    def snapshot(self, max_programs=32):
        """JSON-safe state for WRM debug snapshots / the debug bundle.
        Programs capped to the ``max_programs`` most recently called."""
        with self._lock:
            programs = sorted(
                (dict(e) for e in self.programs.values()),
                key=lambda e: e.get("_seq", 0),
                reverse=True,
            )[:max_programs]
            return {
                "jit_cache_hits": self.jit_cache_hits,
                "jit_cache_misses": self.jit_cache_misses,
                "persistent_cache_hits": self.persistent_cache_hits,
                "persistent_cache_misses": self.persistent_cache_misses,
                "programs_tracked": len(self.programs),
                "programs_evicted": self.programs_evicted,
                "compile_seconds": self.compile_seconds.snapshot(),
                "programs": programs,
            }

    def bind(self, registry):
        """Expose the profiler on a node's registry.  The histogram is the
        SAME instance across every bound registry (process-global compiles);
        counters/gauges are fn-backed reads of the shared state."""
        registry.register(self.compile_seconds)
        for name, help_text, fn in (
            (
                "bqueryd_tpu_jit_cache_hits",
                "instrumented jitted calls served by an already-compiled "
                "program (monotonic)",
                lambda: self.jit_cache_hits,
            ),
            (
                "bqueryd_tpu_jit_cache_misses",
                "instrumented jitted calls that compiled a new program "
                "(monotonic)",
                lambda: self.jit_cache_misses,
            ),
            (
                "bqueryd_tpu_persistent_cache_hits",
                "XLA persistent compile-cache hits (monotonic)",
                lambda: self.persistent_cache_hits,
            ),
            (
                "bqueryd_tpu_persistent_cache_misses",
                "XLA persistent compile-cache misses (monotonic)",
                lambda: self.persistent_cache_misses,
            ),
            (
                "bqueryd_tpu_device_bytes_in_use",
                "device memory in use, summed over local devices",
                self._memory_gauge("bytes_in_use"),
            ),
            (
                "bqueryd_tpu_device_peak_bytes_in_use",
                "high-watermark device memory across local devices",
                self._memory_gauge("peak_bytes_in_use"),
            ),
            (
                "bqueryd_tpu_device_bytes_limit",
                "device memory capacity, summed over local devices",
                self._memory_gauge("bytes_limit"),
            ),
        ):
            registry.gauge(name, help_text, fn=fn)


_profiler = ProgramProfiler()


def profiler():
    """The process-global :class:`ProgramProfiler`."""
    return _profiler


def _reset_for_tests():
    """Test seam: fresh process-global profiler state."""
    global _profiler
    _profiler = ProgramProfiler()
    return _profiler


def instrument(name, jitted):
    """Wrap a jitted callable with compile/call accounting.

    Transparent when: profiling or the obs hot path is disabled, the call
    happens under an outer jax trace (tracer args), or the wrapped object
    does not expose a jit cache.  The wrapper never lets accounting raise
    into the query path."""
    # signatures THIS wrapper has already seen compiled: cache-size growth
    # alone is racy when several threads share one jitted function (an
    # in-process cluster), where thread A's compile of shape X lands inside
    # thread B's before/after window for already-compiled shape Y and would
    # misclassify B's call as a ~0s compile — a seen signature is never
    # re-counted as one
    seen_sigs = set()

    def wrapped(*args, **kwargs):
        from bqueryd_tpu import obs

        cache_size = getattr(jitted, "_cache_size", None)
        if (
            cache_size is None
            or not profiling_enabled()
            or not obs.enabled()
            or not _trace_clean()
        ):
            return jitted(*args, **kwargs)
        try:
            before = cache_size()
        except Exception:
            return jitted(*args, **kwargs)
        t0 = time.perf_counter()
        out = jitted(*args, **kwargs)
        duration = time.perf_counter() - t0
        try:
            signature = _shape_signature(name, args, kwargs)
            compiled = cache_size() > before and signature not in seen_sigs
            if len(seen_sigs) > 4096:  # pathological shape drift backstop
                seen_sigs.clear()
            seen_sigs.add(signature)
            _profiler.record_call(
                name, jitted, args, kwargs,
                compiled=compiled,
                duration_s=duration,
                signature=signature,
            )
        except Exception:
            pass  # accounting must never fail the query
        return out

    wrapped.__name__ = name.rsplit(".", 1)[-1]
    wrapped.__wrapped__ = jitted
    return wrapped


# -- environment facts (stdlib-only: controller processes report these too) --

_runtime_versions = None


def runtime_versions():
    """Installed jax/jaxlib/libtpu/numpy versions via package metadata — no
    import of jax itself, so a controller (or a worker whose backend is
    wedged inside native code) can always answer.  Memoized: installed
    versions cannot change under a running process."""
    global _runtime_versions
    if _runtime_versions is None:
        from importlib import metadata

        out = {}
        for pkg in ("jax", "jaxlib", "libtpu", "libtpu-nightly", "numpy"):
            try:
                out[pkg] = metadata.version(pkg)
            except Exception:
                continue
        _runtime_versions = out
    return dict(_runtime_versions)


def compile_cache_info():
    """The persistent-compile-cache decision as facts: enabled?, resolved
    path, writable?  Mirrors the env logic in ``ops/__init__.py`` WITHOUT
    importing it (no JAX side effects), so heterogeneous-fleet SIGILL triage
    (is worker X actually sharing worker Y's cache dir?) starts from
    ``rpc.info()`` instead of shell archaeology."""
    cc = os.environ.get("BQUERYD_TPU_COMPILE_CACHE", "1")
    platf = (
        os.environ.get("BQUERYD_TPU_PLATFORM")
        or os.environ.get("JAX_PLATFORMS")
        or ""
    )
    tpuish = (
        "tpu" in platf
        or "axon" in platf
        or (not platf and "_AXON_REGISTERED" in os.environ)
    )
    enabled = cc != "0" and (tpuish or cc not in ("", "1"))
    path = None
    writable = False
    if enabled:
        path = cc if cc not in ("", "1") else os.path.join(
            os.path.expanduser("~"), ".cache", "bqueryd_tpu", "jax_cache"
        )
        writable = os.path.isdir(path) and os.access(path, os.W_OK)
    return {"enabled": enabled, "path": path, "writable": writable}
