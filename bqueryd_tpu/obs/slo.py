"""Critical-path attribution + SLO accounting: where did this query's wall go.

The tracing stack (PRs 2-3) predates everything that now determines a
query's latency — batch-window staging (PR 9), retry backoff / hedged
dispatch / replica failover (PR 8), calibrated strategy selection (PR 6),
the device-resident collective merge (PR 7) — so a raw span list can no
longer answer "where did this query's 4 s go" without a human replaying the
dispatch state machine.  This module turns an assembled trace timeline
(:class:`bqueryd_tpu.obs.trace.TraceStore` entries) into an **attribution
record**: the query's wall decomposed into named, NON-OVERLAPPING segments
that must cover >= 95% of the measured wall (bench-gated), the remainder
reported honestly as ``unattributed``.

Attribution is a priority sweep, not a tree walk: spans from concurrent
shard dispatches legitimately overlap on the wall clock, so every instant
of the query interval is charged to the most-specific span active at that
instant (:data:`SEGMENT_PRIORITY`: a kernel beats the calc root it nests
in, worker phases beat the dispatch window, everything beats the groupby
root — whose uncovered residue is ``unattributed``).  Dispatch spans carry
their attempt metadata (retries, ``backoff_s``, hedge flag) as tags;
attribution carves each attempt's backoff window out as ``retry_backoff``
and lists the per-attempt history so a failover-heavy query reads as
"0.8 s backoff + 2 dispatch attempts", not as mystery dispatch time.

On top sits the SLO layer:

* :class:`SLOTracker` — per-client-class accounting.  Classes come from
  ``BQUERYD_TPU_SLO_CLASSES`` (``name:target_s[:objective]`` comma list;
  a ``default`` class always exists); clients declare theirs via
  ``RPC(slo_class=...)`` (envelope key ``slo_class``).  Each finished query
  observes its deadline margin into
  ``bqueryd_tpu_slo_margin_seconds{class=...}``, bumps
  ``bqueryd_tpu_slo_queries_total`` / ``bqueryd_tpu_slo_violations_total``,
  and feeds the rolling-window burn-rate gauges
  ``bqueryd_tpu_slo_burn_rate{class=...,window=...}`` (violation rate over
  the window divided by the class's error budget; 1.0 = burning exactly at
  budget, >1 = the objective will be missed if sustained).
* :class:`SnapshotTimeline` — a bounded ring of periodic controller
  registry snapshots (counters, queue depths, latency quantiles, burn
  rates) behind ``rpc.timeline()``, so a regression can be spotted from
  one verb instead of diffing two hand-taken ``rpc.info()`` dumps.

Control-plane module: stdlib only.
"""

import os
import threading
import time

from bqueryd_tpu.utils.env import env_num

#: span name -> attribution segment.  The single declared mapping the
#: span-coverage lint (``bqueryd_tpu.analysis.spans``) cross-checks against
#: ``messages.SPAN_SCHEMA``: every PUBLIC span name declared there must
#: have a segment here, so a new dispatch path cannot silently ship spans
#: the sweep drops into ``unattributed``.  A dict LITERAL on purpose — the
#: lint parses it from source.
SPAN_CATEGORIES = {
    "groupby": "query",                 # the root: residue = unattributed
    "admission": "admission_wait",
    "batch_window": "batch_window_wait",
    "plan": "plan",
    "dispatch": "dispatch",             # backoff_s tag splits retry_backoff
    "demux": "bundle_demux",
    "calc": "worker_other",             # worker residue outside any phase
    "storage_decode": "storage_decode",
    "prune": "storage_decode",          # chunk pruning is scan-side work
    "filter": "filter",
    "factorize": "align",               # key factorization is alignment work
    "align": "align",
    "join_probe": "join_probe",         # DAG broadcast-join probe gather
    "window_rollup": "window_rollup",   # DAG datetime-bucket key derivation
    "h2d_transfer": "h2d_transfer",
    "kernel": "kernel",
    "d2h_fetch": "d2h_fetch",
    "merge": "collective_merge",
    "reply_serialization": "reply_serialization",
}

#: segments synthesized by attribution (or the client) without a recorded
#: span of their own — declared so the span lint can tell a synthetic
#: segment from an undeclared span name
SYNTHETIC_SEGMENTS = (
    "retry_backoff",        # carved out of dispatch spans via tags.backoff_s
    "hedge_dispatch",       # dispatch spans tagged hedge=True
    "client_deserialize",   # measured client-side, added by RPC.autopsy()
    "unattributed",         # the honest remainder
)

#: sweep priority, most-specific first: where spans overlap, the earliest
#: entry here wins the instant.  Worker phases beat the calc root they nest
#: in; worker spans beat the dispatch window they execute inside; dispatch
#: machinery beats admission/window staging; the "query" root loses to
#: everything (its exclusive residue is what ``unattributed`` reports).
SEGMENT_PRIORITY = (
    "d2h_fetch",
    "kernel",
    "collective_merge",
    "h2d_transfer",
    "filter",
    "join_probe",
    "window_rollup",
    "align",
    "storage_decode",
    "reply_serialization",
    "worker_other",
    "bundle_demux",
    "retry_backoff",
    "hedge_dispatch",
    "dispatch",
    "plan",
    "batch_window_wait",
    "admission_wait",
    "client_deserialize",
    "query",
)

_PRIO = {name: i for i, name in enumerate(SEGMENT_PRIORITY)}

#: attribution coverage the bench / CI smoke gates on
COVERAGE_TARGET = 0.95


def _segment_for(span_name):
    """Segment for a span name; unknown names keep themselves as segment
    (visible in the record instead of vanishing) at dispatch-ish priority."""
    return SPAN_CATEGORIES.get(span_name, span_name)


def _intervals_from_spans(spans):
    """(start, end, segment, span) tuples, with dispatch spans split into
    their backoff window (``retry_backoff``) and live queue/send time, and
    hedge dispatches re-labelled ``hedge_dispatch``."""
    out = []
    for span in spans:
        if not isinstance(span, dict):
            continue
        try:
            start = float(span.get("start_ts"))
            dur = max(float(span.get("duration_s", 0.0)), 0.0)
        except (TypeError, ValueError):
            continue
        name = span.get("name")
        segment = _segment_for(name)
        tags = span.get("tags") or {}
        if segment == "dispatch":
            if tags.get("hedge"):
                out.append((start, start + dur, "hedge_dispatch", span))
                continue
            try:
                backoff = min(max(float(tags.get("backoff_s", 0.0)), 0.0), dur)
            except (TypeError, ValueError):
                backoff = 0.0
            if backoff > 0.0:
                out.append((start, start + backoff, "retry_backoff", span))
                if dur > backoff:
                    out.append((start + backoff, start + dur, "dispatch", span))
                continue
        out.append((start, start + dur, segment, span))
    return out


def attribute(timeline):
    """Build the attribution record for one assembled trace timeline.

    Returns a JSON-safe dict: ``trace_id``, ``ok``, ``wall_s`` (the groupby
    root span's duration — submit to final reply at the controller),
    ``segments`` ({segment: seconds}, non-overlapping by construction,
    summing with ``unattributed`` to ``wall_s``), ``coverage`` (attributed
    fraction of the wall), ``covered_s``, ``attempts`` (per dispatch
    attempt: worker, retries, backoff, hedge — the ``_attempt_history``
    view a client can act on), and ``bundle`` (member share metadata when
    the query rode a shared-scan bundle).  Never raises on malformed
    timelines — attribution is forensics, not the query path."""
    spans = [s for s in (timeline or {}).get("spans") or []
             if isinstance(s, dict)]
    record = {
        "trace_id": (timeline or {}).get("trace_id"),
        "ok": (timeline or {}).get("ok"),
        "wall_s": 0.0,
        "covered_s": 0.0,
        "coverage": 0.0,
        "segments": {},
        "unattributed_s": 0.0,
        "attempts": [],
    }
    root = next((s for s in spans if s.get("name") == "groupby"), None)
    intervals = _intervals_from_spans(spans)
    if root is not None:
        try:
            q0 = float(root.get("start_ts"))
            q1 = q0 + max(float(root.get("duration_s", 0.0)), 0.0)
        except (TypeError, ValueError):
            root = None
    if root is None:
        if not intervals:
            return record
        q0 = min(i[0] for i in intervals)
        q1 = max(i[1] for i in intervals)
    wall = max(q1 - q0, 0.0)
    record["wall_s"] = round(wall, 6)
    if wall <= 0.0:
        return record

    # priority sweep over the elementary intervals of the query window:
    # each instant goes to the most-specific active segment; instants where
    # only the "query" root is active are the unattributed residue.  Event
    # sweep with per-segment active counts — O(n log n) in span count plus
    # O(#segments) per boundary, so a wide fan-out's hundreds of spans stay
    # cheap enough for per-query assembly
    events = []   # (ts, +1/-1, segment)
    for start, end, segment, _span in intervals:
        start, end = max(start, q0), min(end, q1)
        if end > start:
            events.append((start, 1, segment))
            events.append((end, -1, segment))
    events.sort(key=lambda e: e[0])
    bounds = sorted({q0, q1, *(ts for ts, _d, _s in events)})
    active = {}   # segment -> open-span count
    segments = {}
    ei = 0
    for lo, hi in zip(bounds, bounds[1:]):
        while ei < len(events) and events[ei][0] <= lo:
            _ts, delta, segment = events[ei]
            count = active.get(segment, 0) + delta
            if count > 0:
                active[segment] = count
            else:
                active.pop(segment, None)
            ei += 1
        if hi <= lo:
            continue
        best = "query"
        best_prio = _PRIO["query"]
        for segment in active:
            prio = _PRIO.get(segment, _PRIO["dispatch"])
            if prio < best_prio:
                best, best_prio = segment, prio
        segments[best] = segments.get(best, 0.0) + (hi - lo)

    unattributed = segments.pop("query", 0.0)
    covered = sum(segments.values())
    record["segments"] = {
        name: round(seconds, 6)
        for name, seconds in sorted(
            segments.items(), key=lambda kv: -kv[1]
        )
    }
    record["unattributed_s"] = round(unattributed, 6)
    record["covered_s"] = round(covered, 6)
    record["coverage"] = round(covered / wall, 4) if wall else 0.0

    # per-attempt dispatch history (tagged in _record_dispatch_span):
    # each retry with its backoff window, each hedge duplicate, each
    # failover exclusion — the msg's _attempt_history, as the trace sees it
    attempts = []
    failed_spans = []
    for span in spans:
        if span.get("name") != "dispatch":
            continue
        tags = span.get("tags") or {}
        if tags.get("wait"):
            # the send→reply / hedge-race transit windows (one per reply):
            # covered time, not attempts of their own
            continue
        if tags.get("failed"):
            # a failed attempt's in-flight window: an ANNOTATION of the
            # attempt its queue-entry span already represents, folded in
            # below — one entry per physical dispatch attempt
            failed_spans.append((tags, span))
            continue
        attempts.append({
            "worker": tags.get("worker"),
            "retries": tags.get("retries", 0),
            "backoff_s": tags.get("backoff_s", 0.0),
            "hedge": bool(tags.get("hedge")),
            "excluded": tags.get("excluded") or [],
            "start_ts": span.get("start_ts"),
            "duration_s": span.get("duration_s"),
        })
    for tags, span in failed_spans:
        match = next(
            (
                a for a in attempts
                if a["worker"] == tags.get("worker")
                and a["retries"] == tags.get("retries", 0)
                and "failed" not in a
            ),
            None,
        )
        if match is not None:
            match["failed"] = tags.get("failed")
            # how long the shard sat on that worker before failover fired
            match["inflight_s"] = span.get("duration_s")
        else:
            # no matching queue span (e.g. trimmed timeline): keep the
            # failure visible as its own entry rather than dropping it
            attempts.append({
                "worker": tags.get("worker"),
                "retries": tags.get("retries", 0),
                "backoff_s": 0.0,
                "hedge": False,
                "excluded": [],
                "start_ts": span.get("start_ts"),
                "duration_s": span.get("duration_s"),
                "failed": tags.get("failed"),
            })
    attempts.sort(key=lambda a: a.get("start_ts") or 0.0)
    record["attempts"] = attempts

    # shared-scan bundle metadata: the worker spans carry this member's
    # share of the shared wall (tagged at demux) — the true-wall segments
    # above stay untouched; the share contextualizes them per member
    share = None
    for span in spans:
        tags = span.get("tags") or {}
        if "bundle_share" in tags:
            try:
                share = float(tags["bundle_share"])
            except (TypeError, ValueError):
                share = None
            break
    if share is not None:
        worker_segments = {
            "worker_other", "storage_decode", "filter", "align",
            "h2d_transfer", "kernel", "d2h_fetch", "collective_merge",
            "reply_serialization",
        }
        record["bundle"] = {
            "share": round(share, 6),
            # this member's accountable slice of the shared scan phases
            "member_segments": {
                name: round(seconds * share, 6)
                for name, seconds in segments.items()
                if name in worker_segments
            },
        }
    return record


def summarize(record, top=6):
    """Compact attribution view for slow-query ring entries: coverage plus
    the largest segments (full records live in the trace timeline)."""
    if not isinstance(record, dict):
        return None
    segments = record.get("segments") or {}
    ranked = sorted(segments.items(), key=lambda kv: -kv[1])[:top]
    return {
        "coverage": record.get("coverage"),
        "unattributed_s": record.get("unattributed_s"),
        "segments": dict(ranked),
        "attempts": len(record.get("attempts") or ()),
    }


# -- SLO accounting -----------------------------------------------------------

DEFAULT_CLASS = "default"
DEFAULT_TARGET_S = 2.0
DEFAULT_OBJECTIVE = 0.99

#: rolling windows the burn-rate gauges report (label value -> seconds)
BURN_WINDOWS = {"5m": 300.0, "1h": 3600.0}

#: burn-rate bookkeeping granularity: per-class (bucket -> total/violated)
#: counts, NOT raw events — a raw-event cap would silently shrink the 1h
#: window to however long the cap lasts at production QPS (a class that
#: burned hard for 50 minutes then recovered must not report 0.0)
_BURN_BUCKET_S = 60.0


def parse_classes(raw=None):
    """``BQUERYD_TPU_SLO_CLASSES`` -> {class: {"target_s", "objective"}}.

    Format: comma list of ``name:target_s[:objective]`` (e.g.
    ``interactive:0.5:0.999,batch:30``).  Malformed entries are dropped
    (accounting must not take the controller down); a ``default`` class
    always exists so undeclared/unknown client classes have a home."""
    if raw is None:
        raw = os.environ.get("BQUERYD_TPU_SLO_CLASSES", "")
    classes = {}
    for part in (raw or "").split(","):
        part = part.strip()
        if not part:
            continue
        bits = part.split(":")
        name = bits[0].strip()
        if not name:
            continue
        try:
            target = float(bits[1]) if len(bits) > 1 else DEFAULT_TARGET_S
            objective = (
                float(bits[2]) if len(bits) > 2 else DEFAULT_OBJECTIVE
            )
        except ValueError:
            continue
        if target <= 0.0 or not (0.0 < objective < 1.0):
            continue
        classes[name] = {"target_s": target, "objective": objective}
    classes.setdefault(
        DEFAULT_CLASS,
        {"target_s": DEFAULT_TARGET_S, "objective": DEFAULT_OBJECTIVE},
    )
    return classes


class SLOTracker:
    """Per-class SLO accounting on a node's metrics registry.

    ``record()`` is the one entry point: the controller calls it for every
    finished groupby with the query's wall, its deadline margin (absolute
    deadlines win over the class target when the client set one), and
    whether it succeeded.  Derived state: margin histograms, query /
    violation counters, and rolling-window burn rates exposed as
    callback-backed gauges (read at scrape time, no upkeep thread)."""

    #: lock discipline, statically checked by bqueryd_tpu.analysis
    _bqtpu_guarded_ = {"_lock": ("_events",)}

    def __init__(self, registry, classes=None):
        self.classes = classes or parse_classes()
        self._lock = threading.Lock()
        self._events = {}     # class -> {bucket_idx: [total, violated]}
        self._hist = {}
        self._queries = {}
        self._violations = {}
        for name in self.classes:
            self._hist[name] = registry.histogram(
                "bqueryd_tpu_slo_margin_seconds",
                "deadline margin of finished queries (seconds left on the "
                "client deadline, or on the class target when none was "
                "set; negative margins clamp to 0 here and count as "
                "violations)",
                labels={"slo_class": name},
            )
            self._queries[name] = registry.counter(
                "bqueryd_tpu_slo_queries_total",
                "finished queries per SLO class",
                labels={"slo_class": name},
            )
            self._violations[name] = registry.counter(
                "bqueryd_tpu_slo_violations_total",
                "queries that failed or finished past their deadline / "
                "class target",
                labels={"slo_class": name},
            )
            for window in BURN_WINDOWS:
                registry.gauge(
                    "bqueryd_tpu_slo_burn_rate",
                    "rolling-window violation rate over the class error "
                    "budget (1.0 = burning exactly at budget, >1 = the "
                    "objective is being missed)",
                    labels={"slo_class": name, "window": window},
                    fn=(
                        lambda c=name, w=window:
                        self.burn_rate(c, BURN_WINDOWS[w])
                    ),
                )

    def resolve(self, declared):
        """Class for a client-declared name (unknown/None -> default)."""
        return declared if declared in self.classes else DEFAULT_CLASS

    def record(self, slo_class, wall_s, margin_s=None, ok=True, now=None):
        """Account one finished query; returns (class, violated)."""
        now = time.time() if now is None else now
        cls = self.resolve(slo_class)
        target = self.classes[cls]["target_s"]
        if margin_s is None:
            margin_s = target - float(wall_s)
        violated = (not ok) or margin_s < 0.0
        self._hist[cls].observe(max(float(margin_s), 0.0))
        self._queries[cls].inc()
        if violated:
            self._violations[cls].inc()
        # bucketed counts: volume-independent memory (at most window/bucket
        # + 1 buckets per class survive trimming), so sustained QPS can
        # never shrink the labeled window
        bucket = int(now // _BURN_BUCKET_S)
        oldest = int(
            (now - max(BURN_WINDOWS.values())) // _BURN_BUCKET_S
        )
        with self._lock:
            buckets = self._events.setdefault(cls, {})
            slot = buckets.setdefault(bucket, [0, 0])
            slot[0] += 1
            if violated:
                slot[1] += 1
            for idx in [i for i in buckets if i < oldest]:
                del buckets[idx]
        return cls, violated

    def burn_rate(self, slo_class, window_s, now=None):
        """Violation rate over the window divided by the class's error
        budget; 0.0 with no traffic (nothing burning).  Bucketed at
        ``_BURN_BUCKET_S`` granularity (the bucket straddling the window
        edge counts in full — one minute of slack on an hour window)."""
        now = time.time() if now is None else now
        cls = self.resolve(slo_class)
        cutoff = int((now - float(window_s)) // _BURN_BUCKET_S)
        total = violated = 0
        with self._lock:
            for idx, (count, bad) in self._events.get(cls, {}).items():
                if idx >= cutoff:
                    total += count
                    violated += bad
        if not total:
            return 0.0
        budget = 1.0 - self.classes[cls]["objective"]
        return (violated / total) / budget if budget > 0 else 0.0

    def snapshot(self, now=None):
        """JSON-safe per-class state for rpc.timeline() / debug bundles."""
        now = time.time() if now is None else now
        out = {}
        for name, spec in self.classes.items():
            out[name] = {
                "target_s": spec["target_s"],
                "objective": spec["objective"],
                "queries": int(self._queries[name].value),
                "violations": int(self._violations[name].value),
                "burn_rate": {
                    label: round(self.burn_rate(name, seconds, now=now), 4)
                    for label, seconds in BURN_WINDOWS.items()
                },
            }
        return out


# -- controller timeline ring -------------------------------------------------

DEFAULT_TIMELINE_INTERVAL_S = 10.0
DEFAULT_TIMELINE_ENTRIES = 360


def timeline_interval_s():
    """Snapshot period; <= 0 disables the ring.  Read per tick so a live
    controller can be re-tuned (the ring itself is bounded either way)."""
    return env_num(
        "BQUERYD_TPU_TIMELINE_INTERVAL_S", DEFAULT_TIMELINE_INTERVAL_S
    )


class SnapshotTimeline:
    """Bounded ring of periodic registry snapshots behind ``rpc.timeline()``.

    The controller's heartbeat calls :meth:`maybe_snapshot` with a builder
    callable; the ring paces itself (``BQUERYD_TPU_TIMELINE_INTERVAL_S``)
    and keeps the newest ``BQUERYD_TPU_TIMELINE_ENTRIES`` entries, so "what
    changed in the last hour" is one verb instead of two hand-taken
    ``rpc.info()`` dumps diffed by eye."""

    def __init__(self, capacity=None):
        if capacity is None:
            capacity = env_num(
                "BQUERYD_TPU_TIMELINE_ENTRIES", DEFAULT_TIMELINE_ENTRIES,
                int,
            )
        self.capacity = max(1, capacity)
        self._entries = []
        self._last_ts = 0.0
        #: builder failures (logged too): a broken snapshot builder must
        #: not fail invisibly — an empty rpc.timeline() with a non-zero
        #: failure count is a diagnosable state, a silently empty one is
        #: not
        self.failures = 0

    def maybe_snapshot(self, build, now=None):
        """Append ``build()`` if the interval elapsed; returns True when a
        snapshot was taken.  A builder failure never reaches the caller
        (the timeline is monitoring, never the query path) but is logged
        and counted; ``_last_ts`` advances FIRST, so a failing builder is
        retried once per interval, not hot-looped every heartbeat."""
        interval = timeline_interval_s()
        if interval <= 0:
            return False
        now = time.time() if now is None else now
        if now - self._last_ts < interval:
            return False
        self._last_ts = now
        try:
            entry = dict(build() or {})
        except Exception:
            self.failures += 1
            import logging

            logging.getLogger("bqueryd_tpu").exception(
                "timeline snapshot builder failed"
            )
            return False
        entry["ts"] = round(now, 3)
        self._entries.append(entry)
        if len(self._entries) > self.capacity:
            del self._entries[: len(self._entries) - self.capacity]
        return True

    def entries(self):
        """Oldest first, JSON-safe."""
        return list(self._entries)

    def __len__(self):
        return len(self._entries)
