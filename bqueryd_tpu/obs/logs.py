"""Structured logging + slow-query ring buffer.

``JsonLogFormatter`` renders every log record as one JSON object carrying
``trace_id``/``query_id``/``node_id`` so a fleet's logs correlate back to the
RPC trace waterfall (``rpc.trace(trace_id)``).  The correlation fields come
from a contextvar set by the node while it handles a query
(:func:`bind_log_context`), so deep call stacks (kernels, storage) need no
plumbing.  Opt in with ``BQUERYD_TPU_LOG_JSON=1``
(:func:`bqueryd_tpu.configure_logging` installs the formatter).

``SlowQueryLog`` is the controller's ring buffer of offending queries: every
finished groupby whose wall clock exceeds ``BQUERYD_TPU_SLOW_QUERY_MS``
(default 1000; read per call so a live controller can be re-tuned, 0 records
everything) is kept with its plan signature, strategy hints, pruned-shard
count, and per-shard phase breakdown — queryable over ``rpc.slow_queries()``.

Control-plane module: stdlib only.
"""

import collections
import contextlib
import contextvars
import json
import logging
import os
import time

_log_ctx = contextvars.ContextVar("bqueryd_tpu_log_ctx", default=None)

DEFAULT_SLOW_QUERY_MS = 1000.0


def log_context():
    """The correlation dict bound to this thread/task (may be None)."""
    return _log_ctx.get()


@contextlib.contextmanager
def bind_log_context(**fields):
    """Bind correlation fields (trace_id=..., query_id=...) for the block;
    nested binds merge over the outer ones."""
    merged = dict(_log_ctx.get() or {})
    merged.update({k: v for k, v in fields.items() if v is not None})
    token = _log_ctx.set(merged)
    try:
        yield
    finally:
        _log_ctx.reset(token)


class JsonLogFormatter(logging.Formatter):
    """One JSON object per record: ts, level, logger, msg, node_id plus any
    bound correlation fields and exception text."""

    def __init__(self, node_id=None):
        super().__init__()
        self.node_id = node_id

    def format(self, record):
        out = {
            "ts": round(record.created, 6),
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
        }
        if self.node_id is not None:
            out["node_id"] = self.node_id
        ctx = _log_ctx.get()
        if ctx:
            out.update(ctx)
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out, default=str)


def slow_query_threshold_ms():
    """Read per call so a live node can be re-tuned; invalid values fall
    back to the default rather than disabling the log silently."""
    raw = os.environ.get("BQUERYD_TPU_SLOW_QUERY_MS")
    if raw is None:
        return DEFAULT_SLOW_QUERY_MS
    try:
        return float(raw)
    except ValueError:
        return DEFAULT_SLOW_QUERY_MS


class SlowQueryLog:
    """Bounded ring buffer of offenders — bounded by BOTH entry count and
    bytes (``BQUERYD_TPU_SLOW_QUERY_BYTES``, default 4 MiB): entries carry
    per-shard phase breakdowns, so wide queries made the entry-only cap an
    unbounded-memory promise on long-running controllers.  ``evictions``
    counts entries dropped for either reason (exported as a gauge)."""

    DEFAULT_MAX_BYTES = 4 << 20

    def __init__(self, capacity=128, max_bytes=None):
        if max_bytes is None:
            try:
                max_bytes = int(
                    os.environ.get(
                        "BQUERYD_TPU_SLOW_QUERY_BYTES",
                        self.DEFAULT_MAX_BYTES,
                    )
                )
            except ValueError:
                max_bytes = self.DEFAULT_MAX_BYTES
        self.capacity = max(1, capacity)
        self.max_bytes = max(1024, max_bytes)
        self._entries = collections.deque()
        self._sizes = collections.deque()
        self._nbytes = 0
        self.evictions = 0

    def maybe_record(self, wall_s, entry):
        """Record ``entry`` if ``wall_s`` crosses the live threshold.
        Returns True when recorded."""
        from bqueryd_tpu.obs.flightrec import approx_json_bytes

        if wall_s * 1000.0 < slow_query_threshold_ms():
            return False
        record = dict(entry)
        record.setdefault("ts", time.time())
        record["wall_ms"] = round(wall_s * 1000.0, 3)
        size = approx_json_bytes(record)
        self._entries.append(record)
        self._sizes.append(size)
        self._nbytes += size
        while len(self._entries) > self.capacity or (
            self._nbytes > self.max_bytes and len(self._entries) > 1
        ):
            self._entries.popleft()
            self._nbytes -= self._sizes.popleft()
            self.evictions += 1
        return True

    def entries(self):
        """Newest last, JSON-safe."""
        return list(self._entries)

    def entry_for(self, trace_id):
        """The (newest) ring entry for one trace id, or None — lets
        ``rpc.autopsy(trace_id)`` attach the offender's slow-query record
        (plan signature, strategy hints, scaled phase breakdown) to the
        attribution instead of making the operator join two verbs by
        hand."""
        if not trace_id:
            return None
        for record in reversed(self._entries):
            if record.get("trace_id") == trace_id:
                return dict(record)
        return None

    @property
    def nbytes(self):
        return self._nbytes

    def __len__(self):
        return len(self._entries)
