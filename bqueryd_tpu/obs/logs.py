"""Structured logging + slow-query ring buffer.

``JsonLogFormatter`` renders every log record as one JSON object carrying
``trace_id``/``query_id``/``node_id`` so a fleet's logs correlate back to the
RPC trace waterfall (``rpc.trace(trace_id)``).  The correlation fields come
from a contextvar set by the node while it handles a query
(:func:`bind_log_context`), so deep call stacks (kernels, storage) need no
plumbing.  Opt in with ``BQUERYD_TPU_LOG_JSON=1``
(:func:`bqueryd_tpu.configure_logging` installs the formatter).

``SlowQueryLog`` is the controller's ring buffer of offending queries: every
finished groupby whose wall clock exceeds ``BQUERYD_TPU_SLOW_QUERY_MS``
(default 1000; read per call so a live controller can be re-tuned, 0 records
everything) is kept with its plan signature, strategy hints, pruned-shard
count, and per-shard phase breakdown — queryable over ``rpc.slow_queries()``.

Control-plane module: stdlib only.
"""

import collections
import contextlib
import contextvars
import json
import logging
import os
import time

_log_ctx = contextvars.ContextVar("bqueryd_tpu_log_ctx", default=None)

DEFAULT_SLOW_QUERY_MS = 1000.0


def log_context():
    """The correlation dict bound to this thread/task (may be None)."""
    return _log_ctx.get()


@contextlib.contextmanager
def bind_log_context(**fields):
    """Bind correlation fields (trace_id=..., query_id=...) for the block;
    nested binds merge over the outer ones."""
    merged = dict(_log_ctx.get() or {})
    merged.update({k: v for k, v in fields.items() if v is not None})
    token = _log_ctx.set(merged)
    try:
        yield
    finally:
        _log_ctx.reset(token)


class JsonLogFormatter(logging.Formatter):
    """One JSON object per record: ts, level, logger, msg, node_id plus any
    bound correlation fields and exception text."""

    def __init__(self, node_id=None):
        super().__init__()
        self.node_id = node_id

    def format(self, record):
        out = {
            "ts": round(record.created, 6),
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
        }
        if self.node_id is not None:
            out["node_id"] = self.node_id
        ctx = _log_ctx.get()
        if ctx:
            out.update(ctx)
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out, default=str)


def slow_query_threshold_ms():
    """Read per call so a live node can be re-tuned; invalid values fall
    back to the default rather than disabling the log silently."""
    raw = os.environ.get("BQUERYD_TPU_SLOW_QUERY_MS")
    if raw is None:
        return DEFAULT_SLOW_QUERY_MS
    try:
        return float(raw)
    except ValueError:
        return DEFAULT_SLOW_QUERY_MS


class SlowQueryLog:
    """Bounded ring buffer (``capacity`` newest offenders kept)."""

    def __init__(self, capacity=128):
        self._entries = collections.deque(maxlen=max(1, capacity))

    def maybe_record(self, wall_s, entry):
        """Record ``entry`` if ``wall_s`` crosses the live threshold.
        Returns True when recorded."""
        if wall_s * 1000.0 < slow_query_threshold_ms():
            return False
        record = dict(entry)
        record.setdefault("ts", time.time())
        record["wall_ms"] = round(wall_s * 1000.0, 3)
        self._entries.append(record)
        return True

    def entries(self):
        """Newest last, JSON-safe."""
        return list(self._entries)

    def __len__(self):
        return len(self._entries)
