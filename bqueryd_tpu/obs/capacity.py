"""Fleet capacity telemetry: queueing-model saturation accounting.

PR 10 gave every *query* an autopsy; nothing modelled the *fleet*.  This
module is the controller-resident capacity model that answers "is this
cluster saturated, which worker/shard is the bottleneck, and how many
workers would the current load need?" — fed entirely by signals that
already flow, so it costs no new wire traffic:

* **service rate μ** per worker — EWMA of completions over service time,
  derived from the deltas of the ``bqueryd_tpu_worker_groupby_seconds``
  histogram snapshot that rides every WRM heartbeat.  Deltas are
  reset-guarded: a worker process restarting under the same node id resets
  its cumulative counters to zero, and a negative delta must rebase the
  baseline (and count a ``resets`` event), never poison μ;
* **arrival rate λ** per SLO class — tapped at admission submit
  (``AdmissionController.arrival_observer``), bucketed over a rolling
  window;
* **utilization ρ = λ/μ** per worker and fleet-wide, with an M/G/1-style
  (Pollaczek–Khinchine) predicted queue delay
  ``Wq = ρ/(1-ρ) · E[S] · (1+cv²)/2`` whose second moment comes from the
  same histogram's bucket vector — continuously cross-checked against the
  *measured* wait (the admission-wait observer hook plus the
  ``admission_wait``/``dispatch`` segments of finished queries'
  autopsies); the model-vs-measured drift is itself a reported gauge;
* **saturation states** ``ok < warm < saturated < overloaded`` per worker
  and fleet, with hysteresis (a state change must persist
  ``BQUERYD_TPU_CAPACITY_HYSTERESIS_S`` before it takes) so a one-tick
  spike never flaps the advisor;
* **shard heat map** from per-shard dispatch counters — skew detection
  feeding ROADMAP's auto-rebalancing;
* **headroom QPS** and the predicted **saturation knee**
  (``knee_qps = Σμ / shards-per-query`` — the offered QPS at which ρ
  reaches 1), which bench.py's load ramp checks against the measured
  throughput plateau;
* a **shadow advisor**: ``scale_up n`` / ``scale_down n`` /
  ``rebalance shard→worker`` recommendations with the evidence attached —
  surfaced via ``rpc.capacity()``, logged to the flight recorder and
  counters, **never acted on** (a later enforcement PR consumes them).

The worker-side ``pipeline_busy`` WRM key (the PR-4 StageClock snapshot)
feeds per-stage busy deltas so each worker's bottleneck *stage* (decode vs
kernel vs merge) is named beside its ρ.  NOTE: the StageClock is
process-global on the worker — in-process test topologies running several
workers in one process share one clock, so stage shares are advisory
there; μ always comes from the per-node registry histograms.

Control-plane module: stdlib only.
"""

import math
import os
import threading
import time

from bqueryd_tpu.utils.env import env_num

#: the WRM histogram family μ is derived from (same family the health
#: scorer windows): count = completed CalcMessages, sum = service seconds
SERVICE_FAMILY = "bqueryd_tpu_worker_groupby_seconds"

STATE_OK = "ok"
STATE_WARM = "warm"
STATE_SATURATED = "saturated"
STATE_OVERLOADED = "overloaded"

#: severity order; the numeric codes back the fleet-state gauge
STATE_CODES = {
    STATE_OK: 0, STATE_WARM: 1, STATE_SATURATED: 2, STATE_OVERLOADED: 3,
}

#: ρ at which λ has outrun μ by definition (not an env knob: >= 1 means the
#: queue grows without bound while the window's rates hold)
RHO_OVERLOADED = 1.0

#: EWMA smoothing for service-time moments and measured waits
EWMA_ALPHA = 0.3

#: a hot shard is one whose dispatch share exceeds this multiple of the
#: uniform share (skew detection for the rebalance advice)
SHARD_SKEW_FACTOR = 3.0

DEFAULT_WINDOW_S = 60.0
DEFAULT_RHO_WARM = 0.5
DEFAULT_RHO_SATURATED = 0.8
DEFAULT_HYSTERESIS_S = 10.0
DEFAULT_TARGET_RHO = 0.7


def capacity_enabled():
    """Whether the capacity model ingests/evaluates (read per call:
    live-tunable).  The taps are dict bumps under one lock — the 2%
    observability overhead budget covers them — but a kill switch is the
    house rule for every accounting layer."""
    return os.environ.get("BQUERYD_TPU_CAPACITY", "1") != "0"


def window_s():
    """Rolling window the arrival/dispatch rates are measured over."""
    return max(env_num("BQUERYD_TPU_CAPACITY_WINDOW_S", DEFAULT_WINDOW_S),
               1.0)


def rho_warm():
    return env_num("BQUERYD_TPU_CAPACITY_RHO_WARM", DEFAULT_RHO_WARM)


def rho_saturated():
    return env_num(
        "BQUERYD_TPU_CAPACITY_RHO_SATURATED", DEFAULT_RHO_SATURATED
    )


def hysteresis_s():
    return max(
        env_num("BQUERYD_TPU_CAPACITY_HYSTERESIS_S", DEFAULT_HYSTERESIS_S),
        0.0,
    )


def target_rho():
    """The utilization the advisor sizes the fleet for: scale_up asks for
    enough workers to bring ρ back to this, scale_down only sheds workers
    the target still leaves headroom for."""
    rho = env_num("BQUERYD_TPU_CAPACITY_TARGET_RHO", DEFAULT_TARGET_RHO)
    return min(max(rho, 0.05), 0.95)


def classify(rho):
    """Raw (pre-hysteresis) state for a utilization estimate."""
    if rho is None:
        return STATE_OK
    if rho >= RHO_OVERLOADED:
        return STATE_OVERLOADED
    if rho >= rho_saturated():
        return STATE_SATURATED
    if rho >= rho_warm():
        return STATE_WARM
    return STATE_OK


def _bucket_midpoints(bounds):
    """Geometric midpoints of a log-scale bucket vector, plus the +Inf
    overflow slot (approximated one log-step past the last bound) — good
    enough for the E[S²] the P-K formula needs."""
    mids = []
    for i, hi in enumerate(bounds):
        lo = bounds[i - 1] if i else hi / 2.5
        mids.append(math.sqrt(max(lo, 1e-12) * max(hi, 1e-12)))
    mids.append(bounds[-1] * 2.5 if bounds else 1.0)
    return mids


def service_totals(snapshot):
    """(count, sum_seconds, bucket_bounds, bucket_counts) of the worker
    groupby service histogram in a WRM snapshot; zeros when absent or
    malformed (a skewed peer contributes nothing, never poison)."""
    try:
        series = snapshot.get(SERVICE_FAMILY) or []
        count, total = 0, 0.0
        bounds, counts = [], []
        for entry in series:
            ecounts = [int(c) for c in entry.get("counts", ())]
            count += sum(ecounts)
            total += float(entry.get("sum", 0.0))
            ebounds = [float(b) for b in entry.get("buckets", ())]
            if ebounds and not bounds:
                bounds, counts = ebounds, ecounts
            elif ebounds == bounds and len(ecounts) == len(counts):
                counts = [a + b for a, b in zip(counts, ecounts)]
        return count, total, bounds, counts
    except Exception:
        return 0, 0.0, [], []


class _RateWindow:
    """Bucketed event counts over a rolling window (the burn-rate pattern:
    volume-independent memory — at most window/bucket + 1 buckets survive
    trimming).  NOT thread-safe on its own; the model's lock guards it."""

    def __init__(self, bucket_s=5.0):
        self.bucket_s = bucket_s
        self.buckets = {}   # bucket index -> count
        self.first_ts = None

    def add(self, now, n=1):
        idx = int(now // self.bucket_s)
        self.buckets[idx] = self.buckets.get(idx, 0) + n
        if self.first_ts is None:
            self.first_ts = now

    def rate(self, now, horizon_s):
        """Events/second over the trailing horizon; trims expired buckets.
        A window younger than the horizon measures over its own age (cold
        start must not read as a fraction of the eventual rate)."""
        cutoff = int((now - horizon_s) // self.bucket_s)
        for idx in [i for i in self.buckets if i < cutoff]:
            del self.buckets[idx]
        total = sum(
            c for i, c in self.buckets.items() if i >= cutoff
        )
        span = horizon_s
        if self.first_ts is not None:
            span = min(
                horizon_s, max(now - self.first_ts, self.bucket_s)
            )
        return total / span if span > 0 else 0.0

    def total(self, now, horizon_s):
        cutoff = int((now - horizon_s) // self.bucket_s)
        return sum(c for i, c in self.buckets.items() if i >= cutoff)


class _Hysteresis:
    """A state change must persist ``hold_s`` before it takes; flapping
    inputs keep the last stable state."""

    def __init__(self, state=STATE_OK):
        self.state = state
        self.pending = None      # (raw_state, since_ts)

    def update(self, raw, now, hold_s):
        if raw == self.state:
            self.pending = None
            return self.state
        if self.pending is None or self.pending[0] != raw:
            self.pending = (raw, now)
        if now - self.pending[1] >= hold_s:
            self.state = raw
            self.pending = None
        return self.state


class _WorkerModel:
    """Per-worker cumulative baselines + EWMA service moments.  Mutated
    only under the owning CapacityModel's lock."""

    def __init__(self):
        self.last_count = None   # cumulative completions (None = no baseline)
        self.last_sum = 0.0      # cumulative service seconds
        self.last_counts = []    # cumulative bucket vector
        self.last_ts = None
        self.mean_s = None       # EWMA mean service seconds
        self.m2_s = None         # EWMA second moment of service seconds
        self.busy_ewma = None    # EWMA serving fraction of wall
        self.samples = 0         # completions folded into the EWMAs
        self.resets = 0          # counter restarts detected (rebased)
        self.stage_busy = {}     # stage -> cumulative busy baseline
        self.stage_window = {}   # stage -> busy seconds delta (last beat)
        self.wedged = False      # latest advertised device-health latch
        self.pid = None          # advertised worker pid (exact restarts)
        self.hysteresis = _Hysteresis()

    def mu(self):
        """Service rate: CalcMessages per second of service time."""
        if not self.mean_s or self.mean_s <= 0:
            return None
        return 1.0 / self.mean_s

    def cv2(self):
        """Squared coefficient of variation of service time (0 when the
        moments are too cold to say)."""
        if not self.mean_s or self.m2_s is None:
            return 0.0
        return max(self.m2_s / (self.mean_s * self.mean_s) - 1.0, 0.0)


class CapacityModel:
    """The controller's fleet capacity model (see module docstring).

    Ingestion (``absorb_worker`` / ``observe_*``) and evaluation
    (``evaluate``) all run on the controller event loop plus the metrics
    scrape thread, so every mutable structure sits behind one lock; the
    ``on_advice`` callback fires OUTSIDE the lock (it records flight
    events, which take their own lock)."""

    #: lock discipline, statically checked by bqueryd_tpu.analysis
    _bqtpu_guarded_ = {
        "_lock": (
            "_workers", "_arrivals", "_launched",
            "_arrivals_by_class", "_dispatches",
            "_shard_rates", "_shard_workers", "_measured_wait",
            "_measured_wait_n", "_spq_ewma", "_fleet_state", "_last_eval",
            "_last_advice", "_advice_counts",
        ),
    }

    def __init__(self, on_advice=None):
        self._lock = threading.Lock()
        self._workers = {}            # worker_id -> _WorkerModel
        self._arrivals = _RateWindow()          # offered, all classes
        self._launched = _RateWindow()          # queries that opened a run
        self._arrivals_by_class = {}  # slo class -> _RateWindow
        self._dispatches = {}         # worker_id -> _RateWindow
        self._shard_rates = {}        # shard -> _RateWindow
        self._shard_workers = {}      # shard -> {worker_id: last_ts}
        self._measured_wait = None    # EWMA measured queue delay (s)
        self._measured_wait_n = 0
        self._spq_ewma = None         # shards (CalcMessages) per query
        self._fleet_state = _Hysteresis()
        self._last_eval = {}          # cached evaluation (gauges read it)
        self._last_advice = None      # signatures of standing advice
        self._advice_counts = {       # lifetime advice volume by action
            "scale_up": 0, "scale_down": 0, "rebalance": 0,
        }
        #: advice sink: called with each NEW recommendation dict when the
        #: advised action set changes (the controller wires the flight
        #: recorder + counters here); shadow mode — nobody acts on it
        self.on_advice = on_advice

    # -- ingestion ----------------------------------------------------------
    def absorb_worker(self, worker_id, snapshot, pipeline_busy=None,
                      wedged=False, pid=None, now=None):
        """Fold one WRM heartbeat's cumulative totals in.  A worker
        process restarting under the same node id restarts its cumulative
        counters from zero: detected EXACTLY via the advertised ``pid``
        when it changes, and heuristically (totals halving) for peers that
        ship no pid — either way the baseline is rebased, never a
        poisoned negative rate, and the EWMAs survive the restart
        untouched.  ``wedged`` is the advertised device-health latch: a
        wedged worker's μ is excluded from fleet capacity (a hung
        accelerator is not capacity, whatever it measured before it
        latched)."""
        if not capacity_enabled():
            return
        now = time.time() if now is None else now
        count, total, bounds, counts = service_totals(snapshot or {})
        with self._lock:
            model = self._workers.setdefault(worker_id, _WorkerModel())
            if (
                pid is not None and model.pid is not None
                and pid != model.pid
            ):
                # exact restart signal: rebase before the delta math so
                # even a restart the halving heuristic would miss (old
                # count still small) never folds a cross-restart delta
                # into the moments
                model.resets += 1
                model.last_count, model.last_sum = count, total
                model.last_counts = counts
                model.stage_busy = {}
                model.stage_window = {}
            if pid is not None:
                model.pid = pid
            self._absorb_service_locked(
                model, count, total, bounds, counts, now
            )
            self._absorb_stages_locked(model, pipeline_busy)
            model.wedged = bool(wedged)
            model.last_ts = now

    def _absorb_service_locked(self, model, count, total, bounds, counts,
                               now):
        if model.last_count is None:
            model.last_count, model.last_sum = count, total
            model.last_counts = counts
            return
        dcount = count - model.last_count
        dsum = total - model.last_sum
        if dcount < 0 or dsum < -1e-9:
            # cumulative totals went backwards.  Two distinct causes: the
            # worker process RESTARTED under the same node id (totals
            # restart near zero — rebase the baseline, never a negative
            # rate), or the worker's two WRM streams (main loop + liveness
            # thread) delivered snapshots slightly out of order (totals
            # barely below the baseline — drop the stale sample, keep the
            # baseline).  The halving test separates them.
            if count <= model.last_count // 2:
                model.resets += 1
                model.last_count, model.last_sum = count, total
                model.last_counts = counts
            return
        elapsed = (
            now - model.last_ts if model.last_ts is not None else None
        )
        if dcount > 0:
            mean = dsum / dcount
            model.mean_s = (
                mean if model.mean_s is None
                else (1 - EWMA_ALPHA) * model.mean_s + EWMA_ALPHA * mean
            )
            m2 = self._second_moment(
                bounds, counts, model.last_counts, mean
            )
            model.m2_s = (
                m2 if model.m2_s is None
                else (1 - EWMA_ALPHA) * model.m2_s + EWMA_ALPHA * m2
            )
            model.samples += dcount
        if elapsed is not None and elapsed > 0:
            busy = min(max(dsum, 0.0) / elapsed, 1.0)
            model.busy_ewma = (
                busy if model.busy_ewma is None
                else (1 - EWMA_ALPHA) * model.busy_ewma + EWMA_ALPHA * busy
            )
        model.last_count, model.last_sum = count, total
        model.last_counts = counts

    @staticmethod
    def _second_moment(bounds, counts, last_counts, fallback_mean):
        """E[S²] of the heartbeat's completions from the bucket-vector
        delta (geometric midpoints); falls back to the deterministic
        mean² when the vectors don't line up (version skew)."""
        if (
            not bounds
            or len(counts) != len(bounds) + 1
            or len(last_counts) != len(counts)
        ):
            return fallback_mean * fallback_mean
        deltas = [max(a - b, 0) for a, b in zip(counts, last_counts)]
        n = sum(deltas)
        if n <= 0:
            return fallback_mean * fallback_mean
        mids = _bucket_midpoints(bounds)
        return sum(d * m * m for d, m in zip(deltas, mids)) / n

    def _absorb_stages_locked(self, model, pipeline_busy):
        busy = (pipeline_busy or {}).get("busy_seconds")
        if not isinstance(busy, dict):
            return
        for stage, seconds in busy.items():
            try:
                seconds = float(seconds)
            except (TypeError, ValueError):
                continue
            base = model.stage_busy.get(stage)
            if base is not None and seconds >= base:
                # EWMA of per-beat busy deltas: idle beats decay every
                # stage equally (relative ordering — the bottleneck label
                # — survives a quiet spell)
                delta = seconds - base
                prev = model.stage_window.get(stage)
                model.stage_window[stage] = (
                    delta if prev is None
                    else (1 - EWMA_ALPHA) * prev + EWMA_ALPHA * delta
                )
            elif base is not None and seconds > base / 2.0:
                # slightly-backwards cumulative busy: a stale snapshot
                # from the worker's other WRM stream — drop the sample,
                # keep the baseline AND the EWMA (same halving contract as
                # the service-totals path)
                continue
            else:
                # first sight or a reset (restart): drop the stale EWMA,
                # the fresh process rebuilds its own
                model.stage_window.pop(stage, None)
            model.stage_busy[stage] = seconds

    def remove_worker(self, worker_id):
        with self._lock:
            self._workers.pop(worker_id, None)
            self._dispatches.pop(worker_id, None)
            # heat-map hygiene: rebalance evidence must not cite a culled
            # worker as a live holder
            for holders in self._shard_workers.values():
                holders.pop(worker_id, None)

    def observe_arrival(self, slo_class="default", now=None):
        """One offered query at admission (ADMIT/QUEUED and BUSY all count
        toward λ: it is *offered* load, and shed load is exactly what
        saturation looks like; DUPLICATE resubmissions never reach this
        hook)."""
        if not capacity_enabled():
            return
        now = time.time() if now is None else now
        with self._lock:
            self._arrivals.add(now)
            self._arrivals_by_class.setdefault(
                str(slo_class or "default"), _RateWindow()
            ).add(now)

    def observe_launch(self, now=None):
        """One query actually opening a run (solo launch or bundle
        member).  This — not offered arrivals — is the shards-per-query
        denominator: BUSY-shed, queued-then-expired, and superseded
        offers dispatch no shards, and counting them would overestimate
        the knee precisely while the cluster sheds load."""
        if not capacity_enabled():
            return
        now = time.time() if now is None else now
        with self._lock:
            self._launched.add(now)

    def observe_dispatch(self, worker_id, filenames, now=None):
        """One CalcMessage handed to a worker; ``filenames`` is the shard
        group it covers (the heat map counts each member shard)."""
        if not capacity_enabled():
            return
        now = time.time() if now is None else now
        if isinstance(filenames, str):
            filenames = [filenames]
        with self._lock:
            self._dispatches.setdefault(worker_id, _RateWindow()).add(now)
            for shard in filenames or ():
                self._shard_rates.setdefault(shard, _RateWindow()).add(now)
                holders = self._shard_workers.setdefault(shard, {})
                holders[worker_id] = now
                if len(holders) > 16:
                    oldest = min(holders, key=holders.get)
                    holders.pop(oldest, None)

    def observe_queue_wait(self, seconds, source="admission"):
        """A measured queue-delay sample: the admission wait-observer hook
        (queued → launch) or a finished query's autopsy
        ``admission_wait + dispatch`` segments (submit → worker send, the
        wait the M/G/1 prediction models).  EWMA'd; the drift gauge is
        predicted vs this."""
        del source
        if not capacity_enabled():
            return
        try:
            seconds = max(float(seconds), 0.0)
        except (TypeError, ValueError):
            return
        with self._lock:
            self._measured_wait = (
                seconds if self._measured_wait is None
                else (1 - EWMA_ALPHA) * self._measured_wait
                + EWMA_ALPHA * seconds
            )
            self._measured_wait_n += 1

    # -- evaluation ---------------------------------------------------------
    def evaluate(self, now=None):
        """Recompute per-worker/fleet utilization, states (hysteresis
        applied), the shard heat map, and the shadow advice; caches the
        result for the gauges and returns it.  Emits ``on_advice`` for each
        recommendation when the advised action set changes."""
        if not capacity_enabled():
            with self._lock:
                # the kill switch must produce the documented stub, not a
                # frozen pre-disable verdict: stale saturation gauges on a
                # dead model would keep alerts firing forever
                self._last_eval = {}
            return {}
        now = time.time() if now is None else now
        with self._lock:
            result = self._evaluate_locked(now)
            # signatures deliberately EXCLUDE the sizing `n`: near a
            # capacity boundary ceil() quantization flips n every beat,
            # and re-emitting a standing scale_up per flip would flood the
            # flight ring and inflate the advised counters
            signatures = {
                (a["action"], a.get("shard")): a
                for a in result["recommendations"]
            }
            # emit/count only recommendations NOT already standing: a
            # rebalance rec flapping in and out must not re-count the
            # unchanged scale_up rec beside it
            previous = self._last_advice or frozenset()
            fresh = [
                rec for sig, rec in signatures.items()
                if sig not in previous
            ]
            self._last_advice = frozenset(signatures)
            for rec in fresh:
                self._advice_counts[rec["action"]] = (
                    self._advice_counts.get(rec["action"], 0) + 1
                )
            self._last_eval = result
        if fresh and self.on_advice is not None:
            for rec in fresh:
                try:
                    self.on_advice(rec)
                except Exception:
                    pass  # shadow advice must never break the event loop
        return result

    def _evaluate_locked(self, now):
        horizon = window_s()
        hold = hysteresis_s()
        arrival_qps = self._arrivals.rate(now, horizon)
        by_class = {
            cls: round(w.rate(now, horizon), 4)
            for cls, w in self._arrivals_by_class.items()
            if w.total(now, horizon) > 0
        }
        launched_qps = self._launched.rate(now, horizon)
        shard_rate = sum(
            w.rate(now, horizon) for w in self._dispatches.values()
        )
        # shards per SERVED query: the launched rate is the denominator —
        # shed/expired/superseded offers dispatch nothing and must not
        # deflate spq (and thereby inflate the knee) exactly when the
        # cluster sheds load
        if launched_qps > 0 and shard_rate > 0:
            spq = max(shard_rate / launched_qps, 1e-6)
            self._spq_ewma = (
                spq if self._spq_ewma is None
                else (1 - EWMA_ALPHA) * self._spq_ewma + EWMA_ALPHA * spq
            )
        spq = self._spq_ewma or 1.0

        workers = {}
        mu_fleet = 0.0
        measured_workers = 0
        wq_num = wq_den = 0.0
        for worker_id, model in self._workers.items():
            lam = (
                self._dispatches[worker_id].rate(now, horizon)
                if worker_id in self._dispatches else 0.0
            )
            mu = model.mu()
            rate_rho = (lam / mu) if mu else None
            busy = model.busy_ewma
            # utilization: the rate ratio when measurable, tempered by the
            # directly measured serving fraction (max of both — a worker
            # 95% busy is saturated no matter how noisy the λ window is)
            rho = rate_rho
            if busy is not None:
                rho = busy if rho is None else max(rho, busy)
            state = model.hysteresis.update(classify(rho), now, hold)
            wq = None
            if mu and rate_rho is not None:
                if rate_rho < 1.0:
                    wq = (
                        rate_rho / (1.0 - rate_rho)
                        * model.mean_s * (1.0 + model.cv2()) / 2.0
                    )
                    wq = min(wq, horizon)
                else:
                    wq = horizon  # unbounded in-model: cap at the window
                wq_num += lam * wq
                wq_den += lam
            if mu and not model.wedged:
                # a wedged accelerator is not capacity: its (pre-latch) μ
                # must not inflate the knee, so losing a device to a wedge
                # shrinks fleet μ exactly like losing the worker
                mu_fleet += mu
                measured_workers += 1
            bottleneck = None
            if model.stage_window:
                bottleneck = max(
                    model.stage_window, key=model.stage_window.get
                )
            workers[worker_id] = {
                "mu": round(mu, 4) if mu else None,
                "lambda": round(lam, 4),
                "rho": round(rho, 4) if rho is not None else None,
                "state": state,
                "mean_service_s": (
                    round(model.mean_s, 6) if model.mean_s else None
                ),
                "cv2": round(model.cv2(), 4),
                "busy_fraction": (
                    round(busy, 4) if busy is not None else None
                ),
                "samples": model.samples,
                "resets": model.resets,
                "predicted_wait_s": (
                    round(wq, 6) if wq is not None else None
                ),
                "bottleneck_stage": bottleneck,
                "wedged": model.wedged,
            }

        n_workers = len(self._workers)
        knee_qps = (mu_fleet / spq) if mu_fleet > 0 else None
        fleet_rho = (shard_rate / mu_fleet) if mu_fleet > 0 else None
        busys = [
            m.busy_ewma for m in self._workers.values()
            if m.busy_ewma is not None
        ]
        if busys:
            mean_busy = sum(busys) / len(busys)
            fleet_rho = (
                mean_busy if fleet_rho is None
                else max(fleet_rho, mean_busy)
            )
        fleet_state = self._fleet_state.update(
            classify(fleet_rho), now, hold
        )
        predicted_wait = (wq_num / wq_den) if wq_den > 0 else None
        measured_wait = self._measured_wait
        drift = None
        if predicted_wait is not None and measured_wait is not None:
            scale = max(predicted_wait, measured_wait, 0.005)
            drift = (predicted_wait - measured_wait) / scale
        headroom_qps = None
        if knee_qps is not None:
            headroom_qps = max(knee_qps * target_rho() - arrival_qps, 0.0)

        heat = self._shard_heat_locked(now, horizon)
        recommendations = self._advise_locked(
            now=now,
            arrival_qps=arrival_qps,
            shard_rate=shard_rate,
            mu_fleet=mu_fleet,
            measured_workers=measured_workers,
            n_workers=n_workers,
            fleet_state=fleet_state,
            fleet_rho=fleet_rho,
            workers=workers,
            heat=heat,
        )
        return {
            "ts": round(now, 3),
            "window_s": horizon,
            "fleet": {
                "workers": n_workers,
                "measured_workers": measured_workers,
                "coverage": (
                    round(measured_workers / n_workers, 4)
                    if n_workers else 0.0
                ),
                "arrival_qps": round(arrival_qps, 4),
                "launched_qps": round(launched_qps, 4),
                "arrival_qps_by_class": by_class,
                "dispatch_rate": round(shard_rate, 4),
                "shards_per_query": round(spq, 4),
                "mu_dispatches_per_s": round(mu_fleet, 4),
                "knee_qps": (
                    round(knee_qps, 4) if knee_qps is not None else None
                ),
                "utilization": (
                    round(fleet_rho, 4) if fleet_rho is not None else None
                ),
                "state": fleet_state,
                "headroom_qps": (
                    round(headroom_qps, 4)
                    if headroom_qps is not None else None
                ),
                "predicted_queue_delay_s": (
                    round(predicted_wait, 6)
                    if predicted_wait is not None else None
                ),
                "measured_queue_delay_s": (
                    round(measured_wait, 6)
                    if measured_wait is not None else None
                ),
                "measured_wait_samples": self._measured_wait_n,
                "model_drift": (
                    round(drift, 4) if drift is not None else None
                ),
            },
            "workers": workers,
            "shard_heat": heat,
            "recommendations": recommendations,
            "advice_counts": dict(self._advice_counts),
        }

    def _shard_heat_locked(self, now, horizon, top=16):
        entries = []
        for shard, w in list(self._shard_rates.items()):
            rate = w.rate(now, horizon)   # trims expired buckets
            if w.total(now, horizon) <= 0:
                if not w.buckets:
                    # no traffic left anywhere in the window: drop the
                    # shard's bookkeeping so a long-lived controller's
                    # heat map stays bounded by ACTIVE shards
                    del self._shard_rates[shard]
                    self._shard_workers.pop(shard, None)
                continue
            entries.append((rate, shard))
        entries.sort(reverse=True)
        # share/skew denominate over the SUMMED per-shard rate, not the
        # envelope dispatch rate: a batched shard group bumps every member
        # shard per envelope, and the envelope denominator would read a
        # perfectly uniform k-shard group as skew k (spurious rebalance
        # advice at k >= SHARD_SKEW_FACTOR)
        n_shards = len(entries)
        total_rate = sum(rate for rate, _shard in entries)
        uniform = (total_rate / n_shards) if n_shards else 0.0
        heat = []
        for rate, shard in entries[:top]:
            share = (rate / total_rate) if total_rate > 0 else 0.0
            heat.append({
                "shard": shard,
                "rate": round(rate, 4),
                "share": round(share, 4),
                "skew": (
                    round(rate / uniform, 2) if uniform > 0 else None
                ),
                "workers": sorted(self._shard_workers.get(shard, ())),
            })
        return heat

    def _advise_locked(self, now, arrival_qps, shard_rate, mu_fleet,
                       measured_workers, n_workers, fleet_state, fleet_rho,
                       workers, heat):
        """Shadow recommendations with evidence.  No traffic in the window
        means no evidence — an idle cluster gets no advice (especially not
        a scale_down loop)."""
        del now
        recs = []
        if arrival_qps <= 0 or not n_workers or not measured_workers:
            return recs
        # sizing is in USABLE workers (measured, non-wedged — the same
        # population μ_fleet sums over): a fleet of 4 with 2 wedged has 2
        # usable workers, and scale_up must size the gap from THAT, or
        # wedged capacity that isn't capacity double-counts
        usable = measured_workers
        mu_avg = mu_fleet / usable if usable else None
        workers_needed = None
        if mu_avg:
            workers_needed = max(
                math.ceil(shard_rate / (target_rho() * mu_avg)), 1
            )
        if fleet_state in (STATE_SATURATED, STATE_OVERLOADED):
            n = 1
            if workers_needed is not None:
                n = max(workers_needed - usable, 1)
            recs.append({
                "action": "scale_up",
                "n": n,
                "reason": (
                    f"fleet {fleet_state}: utilization "
                    f"{fleet_rho if fleet_rho is not None else 'n/a'} vs "
                    f"target {target_rho()}"
                ),
                "evidence": {
                    "fleet_rho": fleet_rho,
                    "arrival_qps": round(arrival_qps, 4),
                    "dispatch_rate": round(shard_rate, 4),
                    "mu_fleet": round(mu_fleet, 4),
                    "workers": n_workers,
                    "usable_workers": usable,
                    "workers_needed": workers_needed,
                },
            })
        elif (
            fleet_state == STATE_OK
            and usable > 1
            and workers_needed is not None
            and workers_needed < usable
            and fleet_rho is not None
            and fleet_rho < 0.5 * rho_warm()
        ):
            recs.append({
                "action": "scale_down",
                "n": usable - workers_needed,
                "reason": (
                    f"fleet idle: utilization {fleet_rho} — "
                    f"{workers_needed} worker(s) would hold ρ at "
                    f"{target_rho()}"
                ),
                "evidence": {
                    "fleet_rho": fleet_rho,
                    "arrival_qps": round(arrival_qps, 4),
                    "workers": n_workers,
                    "usable_workers": usable,
                    "workers_needed": workers_needed,
                },
            })
        # rebalance: a skewed-hot shard while some worker sits cool
        if heat and len(self._shard_rates) >= 4:
            hottest = heat[0]
            cool = [
                wid for wid, w in workers.items()
                if w["state"] == STATE_OK
                and wid not in hottest["workers"]
            ]
            hot_worker_states = [
                workers[wid]["state"] for wid in hottest["workers"]
                if wid in workers
            ]
            if (
                hottest.get("skew") is not None
                and hottest["skew"] >= SHARD_SKEW_FACTOR
                and cool
                and any(s != STATE_OK for s in hot_worker_states)
            ):
                recs.append({
                    "action": "rebalance",
                    "shard": hottest["shard"],
                    "to_worker": min(
                        cool,
                        key=lambda wid: workers[wid]["rho"] or 0.0,
                    ),
                    "reason": (
                        f"shard {hottest['shard']} takes "
                        f"{hottest['skew']}x the uniform dispatch share "
                        "while a holder is hot and another worker is ok"
                    ),
                    "evidence": {
                        "share": hottest["share"],
                        "skew": hottest["skew"],
                        "holders": hottest["workers"],
                    },
                })
        return recs

    # -- read surface -------------------------------------------------------
    def snapshot(self):
        """The cached last evaluation (JSON-safe) — ``rpc.capacity()`` and
        the debug bundle call :meth:`evaluate` first for freshness; the
        gauges read this without recomputing."""
        with self._lock:
            out = dict(self._last_eval)
        out["enabled"] = capacity_enabled()
        return out

    def fleet_gauge(self, field, default=0.0):
        """One fleet-level number for a callback gauge (NaN-free)."""
        with self._lock:
            fleet = self._last_eval.get("fleet") or {}
        value = fleet.get(field)
        if field == "state":
            return STATE_CODES.get(value, 0)
        return default if value is None else value

    def advice_count(self, action):
        with self._lock:
            return self._advice_counts.get(action, 0)

    def worker_resets(self):
        """Total WRM counter restarts detected (the satellite's guard made
        visible: a restarting fleet shows up here, not as poisoned μ)."""
        with self._lock:
            return sum(m.resets for m in self._workers.values())
