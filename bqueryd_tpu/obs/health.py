"""Worker health scoring: observability folded back into placement.

PR 2 made worker latency histograms ride WRM heartbeats; this module is the
missing half of the loop — the controller folds those snapshots into rolling
per-worker baselines and the dispatch path *acts* on them (the shape the
Taurus near-data-processing line argues for: health signals in the placement
decision, not on a dashboard).

Per worker, per heartbeat, the :class:`HealthScorer` records (groupby count,
latency sum, error-counter value, backend_wedged) samples and keeps a time
window of them.  Classification, strictest first:

* ``wedged``   — the worker's own device-health latch says its accelerator
  backend is hung (it still serves host-kernel results, so it is NOT
  removed — just last in line);
* ``degraded`` — its windowed error rate crossed ``error_rate_threshold``
  (with a minimum error count, so one blip never flags), or its windowed
  mean query latency is ``latency_factor``x the fleet median (computed over
  workers with enough samples — a lone worker is never an outlier of one);
* ``ok``       — everything else, including workers too young to judge
  (innocent until measured).

``ControllerNode.find_free_worker`` prefers ``ok`` candidates and falls back
to degraded/wedged ones only when no healthy holder of the shard is free —
deprioritized, never excluded: a degraded worker that is the sole holder
still serves.  ``BQUERYD_TPU_HEALTH_ROUTING=0`` turns the preference off
(scoring and ``rpc.health()`` stay live).

Control-plane module: stdlib only.
"""

import collections
import os
import statistics
import threading
import time

STATUS_OK = "ok"
STATUS_DEGRADED = "degraded"
STATUS_WEDGED = "wedged"

#: the worker-side histogram family the latency baseline is derived from
LATENCY_FAMILY = "bqueryd_tpu_worker_groupby_seconds"


def routing_enabled():
    """Whether dispatch deprioritizes non-ok workers (read per call)."""
    return os.environ.get("BQUERYD_TPU_HEALTH_ROUTING", "1") != "0"


def _latency_totals(snapshot):
    """(count, sum_seconds) of the worker groupby histogram in a WRM
    histogram snapshot; (0, 0.0) when absent/malformed."""
    try:
        series = snapshot.get(LATENCY_FAMILY) or []
        count = 0
        total = 0.0
        for entry in series:
            count += sum(int(c) for c in entry.get("counts", ()))
            total += float(entry.get("sum", 0.0))
        return count, total
    except Exception:
        return 0, 0.0


class HealthScorer:
    """Rolling per-worker latency/error baselines + outlier classification."""

    def __init__(self, window_s=300.0, min_samples=5, latency_factor=3.0,
                 error_rate_threshold=0.25, min_errors=3,
                 latency_floor_s=0.001):
        self.window_s = window_s
        #: min completed queries in the window before a worker can be a
        #: latency outlier (or anchor the fleet median)
        self.min_samples = min_samples
        self.latency_factor = latency_factor
        self.error_rate_threshold = error_rate_threshold
        self.min_errors = min_errors
        #: fleet medians under this are noise, not a baseline to be 3x of
        self.latency_floor_s = latency_floor_s
        self._lock = threading.Lock()
        self._samples = {}   # worker_id -> deque[(ts, count, sum, errors)]
        self._wedged = {}    # worker_id -> bool (latest advertised latch)
        self._pids = {}      # worker_id -> advertised pid (exact restarts)
        # statuses() is on the dispatch hot path (one call per placed
        # shard) but its inputs change only on observe/remove (heartbeat
        # cadence): memoize on a revision counter, same pattern as the
        # controller's _worker_hist_cache
        self._rev = 0
        self._statuses_cache = (-1, None)

    def observe(self, worker_id, snapshot=None, wedged=False, errors=None,
                pid=None, now=None):
        """Fold one WRM's worth of signals in (idempotent per heartbeat:
        identical cumulative totals just extend the window)."""
        now = time.time() if now is None else now
        count, total = _latency_totals(snapshot or {})
        try:
            errors = int(errors or 0)
        except (TypeError, ValueError):
            errors = 0
        with self._lock:
            window = self._samples.setdefault(
                worker_id, collections.deque()
            )
            last_pid = self._pids.get(worker_id)
            if pid is not None:
                self._pids[worker_id] = pid
            if window and pid is not None and last_pid is not None \
                    and pid != last_pid:
                # exact restart signal (the WRM advertises the pid):
                # rebase even when the totals alone wouldn't prove it
                window.clear()
            if window:
                _ts, lcount, ltotal, lerr = window[-1]
                if count < lcount and count <= lcount // 2:
                    # the worker process restarted under the same node id:
                    # its cumulative histogram/counter totals reset to
                    # zero.  The window deltas assume monotonicity — left
                    # alone, max(last-first, 0) would clamp this worker's
                    # windowed throughput/error rate to 0 until the
                    # pre-restart samples age out, hiding a genuinely slow
                    # or erroring restarted worker.  Rebase: drop the
                    # pre-restart samples and let the fresh process build
                    # its own baseline (innocent until measured, same as a
                    # brand-new worker).  The halving test keeps slightly
                    # out-of-order snapshots from the worker's two WRM
                    # streams (main loop + liveness thread) from reading
                    # as restarts — those deltas already clamp at 0.
                    window.clear()
            window.append((now, count, total, errors))
            cutoff = now - self.window_s
            while len(window) > 1 and window[0][0] < cutoff:
                window.popleft()
            self._wedged[worker_id] = bool(wedged)
            self._rev += 1

    def remove(self, worker_id):
        with self._lock:
            self._samples.pop(worker_id, None)
            self._wedged.pop(worker_id, None)
            self._pids.pop(worker_id, None)
            self._rev += 1

    def _window_stats(self, window):
        """Deltas across the window: completed queries, mean latency,
        errors, error rate."""
        first, last = window[0], window[-1]
        dcount = max(last[1] - first[1], 0)
        dsum = max(last[2] - first[2], 0.0)
        derr = max(last[3] - first[3], 0)
        mean = (dsum / dcount) if dcount else None
        attempts = dcount + derr
        error_rate = (derr / attempts) if attempts else 0.0
        return {
            "queries": dcount,
            "mean_latency_s": None if mean is None else round(mean, 6),
            "errors": derr,
            "error_rate": round(error_rate, 4),
        }

    def statuses(self, now=None):
        """``{worker_id: {"status", "reason", ...window stats...}}``."""
        with self._lock:
            rev = self._rev
            cached_rev, cached = self._statuses_cache
            if cached_rev == rev and cached is not None:
                return cached
            windows = {
                wid: self._window_stats(window)
                for wid, window in self._samples.items()
                if window
            }
            wedged = dict(self._wedged)
        means = [
            s["mean_latency_s"]
            for s in windows.values()
            if s["mean_latency_s"] is not None
            and s["queries"] >= self.min_samples
        ]
        fleet_median = statistics.median(means) if means else None
        out = {}
        for wid, stats in windows.items():
            status, reason = STATUS_OK, None
            if wedged.get(wid):
                status = STATUS_WEDGED
                reason = "backend_wedged latch advertised in WRM"
            elif (
                stats["errors"] >= self.min_errors
                and stats["error_rate"] > self.error_rate_threshold
            ):
                status = STATUS_DEGRADED
                reason = (
                    f"error rate {stats['error_rate']:.0%} over "
                    f"{stats['errors']} errors in window"
                )
            elif (
                fleet_median is not None
                and fleet_median > self.latency_floor_s
                and stats["queries"] >= self.min_samples
                and stats["mean_latency_s"] is not None
                and stats["mean_latency_s"]
                > self.latency_factor * fleet_median
            ):
                status = STATUS_DEGRADED
                reason = (
                    f"mean latency {stats['mean_latency_s']:.3f}s > "
                    f"{self.latency_factor:.1f}x fleet median "
                    f"{fleet_median:.3f}s"
                )
            entry = dict(stats)
            entry["status"] = status
            entry["wedged"] = bool(wedged.get(wid))
            if reason:
                entry["reason"] = reason
            if fleet_median is not None:
                entry["fleet_median_latency_s"] = round(fleet_median, 6)
            out[wid] = entry
        self._statuses_cache = (rev, out)
        return out

    def status(self, worker_id):
        """One worker's status string (``ok`` when unknown)."""
        return self.statuses().get(worker_id, {}).get("status", STATUS_OK)

    def healthy_subset(self, worker_ids):
        """The ``ok`` members of ``worker_ids`` (cheap single scoring pass);
        used by dispatch to prefer healthy holders of a shard."""
        statuses = self.statuses()
        return [
            wid for wid in worker_ids
            if statuses.get(wid, {}).get("status", STATUS_OK) == STATUS_OK
        ]
