"""Typed metrics primitives + per-node registry + Prometheus text rendering.

The reference's entire observability surface was one client-side wall clock
(reference bqueryd/rpc.py:128-129); this build had an untyped ``counters``
dict on the controller and nothing anywhere else.  This module replaces both
with the standard three primitives:

* :class:`Counter`   — monotonic (plus an explicit ``set_total`` seam so the
  controller's dict-compatible counter view can mirror writes);
* :class:`Gauge`     — settable, or callback-backed (``fn=``) so liveness
  values (RSS, queue depth, wedge latch) are read at render time;
* :class:`Histogram` — FIXED log-scale latency buckets
  (:data:`LATENCY_BUCKETS_S`), stored as a non-cumulative per-bucket count
  vector so merging histograms across workers is a plain vector add
  (:func:`merge_histogram_snapshots`) — the controller aggregates every
  worker's phase histograms in ``get_info``/gossip without parsing text.

A :class:`MetricsRegistry` is **per node instance**, not process-global: the
test topology (and bench) runs controller + workers as threads of one
process, and their metrics must not bleed into each other.  Rendering follows
the Prometheus text exposition format v0.0.4; every metric name must match
``^bqueryd_tpu_[a-z0-9_]+$`` and carry help text (:meth:`MetricsRegistry.lint`
enforces both, plus the identical-bucket merge precondition — tests invoke it
against live node registries).

Control-plane module: stdlib only (no numpy/JAX).
"""

import math
import os
import re
import threading

METRIC_NAME_RE = re.compile(r"^bqueryd_tpu_[a-z0-9_]+$")

#: Fixed log-scale latency buckets (seconds), ~2.5x steps from 100 µs to 60 s.
#: A module constant, never instance-configurable for latency metrics: every
#: node must hold the identical vector or the controller's cross-worker
#: bucket-vector addition would silently mis-merge (lint enforces this).
LATENCY_BUCKETS_S = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 60.0,
)

#: Fixed log-scale payload-size buckets (bytes), 4x steps from 256 B to
#: 1 GiB.  Same contract as LATENCY_BUCKETS_S: a module constant, never
#: instance-configurable, so every node holds the identical vector and
#: cross-worker merging stays bucket-vector addition (lint-enforced — a
#: size histogram with latency buckets would be as wrong as the reverse).
BYTES_BUCKETS = tuple(float(256 << (2 * i)) for i in range(12))


def _fmt_value(v):
    if v == math.inf:
        return "+Inf"
    if isinstance(v, float) and v.is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(v) if isinstance(v, float) else str(v)


def _fmt_labels(labels):
    if not labels:
        return ""
    inner = ",".join(
        '%s="%s"' % (k, str(v).replace("\\", r"\\").replace('"', r"\""))
        for k, v in sorted(labels.items())
    )
    return "{%s}" % inner


class Counter:
    """Monotonic counter.  ``inc`` is the public surface; ``set_total`` exists
    only for the controller's dict-compatible mirror (RegistryCounters), which
    assigns absolute values — it must never go backwards in normal use."""

    kind = "counter"

    #: lock discipline, statically checked by bqueryd_tpu.analysis
    _bqtpu_guarded_ = {"_lock": ("_value",)}

    def __init__(self, name, help_text, labels=None):
        self.name = name
        self.help = help_text
        self.labels = dict(labels or {})
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount=1.0):
        with self._lock:
            self._value += amount

    def set_total(self, value):
        with self._lock:
            self._value = float(value)

    @property
    def value(self):
        with self._lock:
            return self._value

    def samples(self):
        with self._lock:
            return [(self.name, self.labels, self._value)]


class Gauge:
    """Settable value, or callback-backed (``fn``) read at render time."""

    kind = "gauge"

    #: lock discipline, statically checked by bqueryd_tpu.analysis
    _bqtpu_guarded_ = {"_lock": ("_value",)}

    def __init__(self, name, help_text, labels=None, fn=None):
        self.name = name
        self.help = help_text
        self.labels = dict(labels or {})
        self._fn = fn
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, value):
        with self._lock:
            self._value = float(value)

    def inc(self, amount=1.0):
        with self._lock:
            self._value += amount

    def dec(self, amount=1.0):
        self.inc(-amount)

    @property
    def value(self):
        if self._fn is not None:
            try:
                return float(self._fn())
            except Exception:
                # a gauge callback must never break rendering (e.g. psutil
                # gone, device probe raising); NaN marks it unreadable
                return float("nan")
        with self._lock:
            return self._value

    def samples(self):
        return [(self.name, self.labels, self.value)]


class Histogram:
    """Fixed-bucket histogram with vector-add mergeable counts.

    Internally stores NON-cumulative per-bucket counts (len(buckets)+1, the
    last slot is the +Inf overflow) plus a running sum; rendering converts to
    Prometheus cumulative ``_bucket{le=...}`` samples.  ``counts`` vectors
    from different nodes merge by element-wise addition as long as the bucket
    vectors are identical — the lint's merge precondition."""

    kind = "histogram"

    #: lock discipline, statically checked by bqueryd_tpu.analysis
    _bqtpu_guarded_ = {"_lock": ("_counts", "_sum")}

    def __init__(self, name, help_text, labels=None, buckets=LATENCY_BUCKETS_S):
        self.name = name
        self.help = help_text
        self.labels = dict(labels or {})
        self.buckets = tuple(float(b) for b in buckets)
        self._counts = [0] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._lock = threading.Lock()

    def observe(self, value):
        value = float(value)
        # linear scan beats bisect at this bucket count for typical (small)
        # latencies, and the loop body is branch-predictable
        idx = len(self.buckets)
        for i, b in enumerate(self.buckets):
            if value <= b:
                idx = i
                break
        with self._lock:
            self._counts[idx] += 1
            self._sum += value

    @property
    def count(self):
        with self._lock:
            return sum(self._counts)

    @property
    def sum(self):
        with self._lock:
            return self._sum

    def snapshot(self):
        """JSON-safe state: {"buckets", "counts", "sum"} (counts non-cumulative)."""
        with self._lock:
            return {
                "buckets": list(self.buckets),
                "counts": list(self._counts),
                "sum": self._sum,
            }

    def samples(self):
        with self._lock:
            counts = list(self._counts)
            total_sum = self._sum
        out = []
        cumulative = 0
        for b, c in zip(self.buckets, counts):
            cumulative += c
            labels = dict(self.labels)
            labels["le"] = _fmt_value(float(b))
            out.append((self.name + "_bucket", labels, cumulative))
        cumulative += counts[-1]
        inf_labels = dict(self.labels)
        inf_labels["le"] = "+Inf"
        out.append((self.name + "_bucket", inf_labels, cumulative))
        out.append((self.name + "_sum", self.labels, total_sum))
        out.append((self.name + "_count", self.labels, cumulative))
        return out


class MetricsRegistry:
    """Per-node metric store: get-or-create by (name, label set), grouped
    into families for rendering.  All mutating/creating calls are
    lock-protected; the hot path (a created metric's ``inc``/``observe``)
    takes only the metric's own lock."""

    #: lock discipline, statically checked by bqueryd_tpu.analysis
    _bqtpu_guarded_ = {"_lock": ("_metrics", "_families")}

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics = {}   # (name, labels-frozenset) -> metric
        self._families = {}  # name -> (kind, help)

    def _get_or_create(self, cls, name, help_text, labels, **kw):
        key = (name, frozenset((labels or {}).items()))
        with self._lock:
            hit = self._metrics.get(key)
            if hit is not None:
                return hit
            family = self._families.get(name)
            if family is not None and family[0] != cls.kind:
                raise ValueError(
                    f"metric {name!r} already registered as {family[0]}"
                )
            metric = cls(name, help_text, labels=labels, **kw)
            self._metrics[key] = metric
            self._families.setdefault(name, (cls.kind, help_text))
            return metric

    def register(self, metric):
        """Adopt an EXISTING metric instance (process-global metrics like
        the compile profiler's histogram, shared across every node registry
        in one process).  Re-registering the same instance is a no-op; a
        different instance under a taken (name, labels) key is an error."""
        key = (metric.name, frozenset(metric.labels.items()))
        with self._lock:
            hit = self._metrics.get(key)
            if hit is metric:
                return metric
            if hit is not None:
                raise ValueError(
                    f"metric {metric.name!r} already registered with a "
                    "different instance"
                )
            family = self._families.get(metric.name)
            if family is not None and family[0] != metric.kind:
                raise ValueError(
                    f"metric {metric.name!r} already registered as "
                    f"{family[0]}"
                )
            self._metrics[key] = metric
            self._families.setdefault(metric.name, (metric.kind, metric.help))
            return metric

    def counter(self, name, help_text, labels=None):
        return self._get_or_create(Counter, name, help_text, labels)

    def gauge(self, name, help_text, labels=None, fn=None):
        return self._get_or_create(Gauge, name, help_text, labels, fn=fn)

    def histogram(self, name, help_text, labels=None,
                  buckets=LATENCY_BUCKETS_S):
        return self._get_or_create(
            Histogram, name, help_text, labels, buckets=buckets
        )

    def metrics(self):
        with self._lock:
            return list(self._metrics.values())

    # -- rendering ----------------------------------------------------------
    def render(self):
        """Prometheus text exposition format v0.0.4."""
        with self._lock:
            metrics = list(self._metrics.values())
            families = dict(self._families)
        by_family = {}
        for metric in metrics:
            by_family.setdefault(metric.name, []).append(metric)
        lines = []
        for name in sorted(by_family):
            kind, help_text = families[name]
            lines.append(f"# HELP {name} {help_text}")
            lines.append(f"# TYPE {name} {kind}")
            for metric in by_family[name]:
                for sample_name, labels, value in metric.samples():
                    lines.append(
                        f"{sample_name}{_fmt_labels(labels)} "
                        f"{_fmt_value(value)}"
                    )
        return "\n".join(lines) + "\n"

    def histogram_snapshot(self):
        """All histograms as a JSON-safe mergeable snapshot — rides worker
        WRMs so the controller can aggregate by bucket-vector addition:
        ``{name: [{"labels": {...}, "buckets": [...], "counts": [...],
        "sum": s}, ...]}``."""
        out = {}
        for metric in self.metrics():
            if metric.kind != "histogram":
                continue
            entry = metric.snapshot()
            entry["labels"] = dict(metric.labels)
            out.setdefault(metric.name, []).append(entry)
        return out

    # -- self-check ---------------------------------------------------------
    def lint(self):
        """Registry self-check (invoked from tests): every metric name
        matches METRIC_NAME_RE (counters may suffix ``_total``), has
        non-empty help text, and every histogram carries one of the shared
        module bucket vectors — LATENCY_BUCKETS_S for latencies,
        BYTES_BUCKETS for sizes (the cross-node merge precondition).
        Returns a list of violation strings — empty means clean."""
        problems = []
        for metric in self.metrics():
            base = metric.name
            if base.endswith("_total"):
                base = base[: -len("_total")]
            if not METRIC_NAME_RE.match(base):
                problems.append(f"{metric.name}: name fails {METRIC_NAME_RE.pattern}")
            if not (metric.help or "").strip():
                problems.append(f"{metric.name}: missing help text")
            if metric.kind == "histogram" and metric.buckets not in (
                tuple(LATENCY_BUCKETS_S), tuple(BYTES_BUCKETS)
            ):
                problems.append(
                    f"{metric.name}: bucket vector is neither "
                    "LATENCY_BUCKETS_S nor BYTES_BUCKETS (cross-node "
                    "merge precondition: buckets must be a shared module "
                    "constant)"
                )
            for label in metric.labels:
                if not re.match(r"^[a-z][a-z0-9_]*$", label):
                    problems.append(f"{metric.name}: bad label name {label!r}")
        return problems


def readme_coverage_problems(registries, readme_text):
    """Doc-coverage lint (run from tests alongside :meth:`MetricsRegistry.lint`
    against live node registries): every registered metric family must be
    named in the README's metrics documentation, or operators discover
    metrics by grepping source.  Returns violation strings, empty = clean."""
    problems = []
    seen = set()
    for registry in registries:
        for metric in registry.metrics():
            if metric.name in seen:
                continue
            seen.add(metric.name)
            if metric.name not in readme_text:
                problems.append(
                    f"{metric.name}: registered but missing from the README "
                    "metrics table"
                )
    return sorted(problems)


def quantile_from_snapshot(entry, q):
    """Approximate quantile from one histogram snapshot entry
    (``{"buckets", "counts", ...}``, counts non-cumulative): the upper
    bound of the bucket where the cumulative count crosses ``q``.  The
    +Inf overflow slot reports the last finite bound (a ceiling is still
    actionable; None would hide the signal).  None on empty/malformed
    entries — the timeline ring snapshots latency quantiles per tick and a
    cold histogram must render as "no data", not 0."""
    try:
        buckets = list(entry["buckets"])
        counts = list(entry["counts"])
    except (KeyError, TypeError):
        return None
    total = sum(counts)
    if total <= 0 or len(counts) != len(buckets) + 1:
        return None
    threshold = max(float(q), 0.0) * total
    cumulative = 0
    for bound, count in zip(buckets, counts):
        cumulative += count
        if cumulative >= threshold:
            return float(bound)
    return float(buckets[-1]) if buckets else None


def merge_histogram_snapshots(snapshots):
    """Aggregate per-worker histogram snapshots by bucket-vector addition.

    ``snapshots`` is an iterable of :meth:`MetricsRegistry.histogram_snapshot`
    dicts (one per worker).  Series merge when (name, labels) match AND the
    bucket vectors are identical; a mismatched vector (version skew) is
    surfaced under ``"_skipped"`` instead of silently corrupting the sums.
    """
    merged = {}   # name -> {labels_key: {"labels", "buckets", "counts", "sum"}}
    skipped = []
    for snap in snapshots:
        if not isinstance(snap, dict):
            continue
        for name, series in snap.items():
            if not isinstance(series, list):
                continue
            for entry in series:
                try:
                    labels = dict(entry.get("labels") or {})
                    buckets = list(entry["buckets"])
                    counts = list(entry["counts"])
                    esum = float(entry.get("sum", 0.0))
                except (KeyError, TypeError, ValueError):
                    skipped.append(name)
                    continue
                key = frozenset(labels.items())
                slot = merged.setdefault(name, {}).get(key)
                if slot is None:
                    merged[name][key] = {
                        "labels": labels,
                        "buckets": buckets,
                        "counts": counts,
                        "sum": esum,
                    }
                elif slot["buckets"] != buckets or len(
                    slot["counts"]
                ) != len(counts):
                    skipped.append(name)
                else:
                    slot["counts"] = [
                        a + b for a, b in zip(slot["counts"], counts)
                    ]
                    slot["sum"] += esum
    out = {
        name: list(by_labels.values()) for name, by_labels in merged.items()
    }
    if skipped:
        out["_skipped"] = sorted(set(skipped))
    return out


class RegistryCounters(dict):
    """The controller's ``counters`` dict, registry-backed.

    A drop-in dict (every existing ``counters["x"] += 1`` call site and the
    ``dict(self.counters)`` snapshots in ``get_info``/bench keep working
    verbatim) whose writes mirror into typed registry :class:`Counter`
    instances named ``bqueryd_tpu_<key>_total`` — so the same numbers appear
    in the Prometheus exposition without double bookkeeping at call sites."""

    def __init__(self, registry, spec):
        """``spec``: ordered mapping of dict key -> help text."""
        super().__init__()
        self._registry = registry
        self._mirror = {}
        for key, help_text in spec.items():
            self._mirror[key] = registry.counter(
                f"bqueryd_tpu_{key}_total", help_text
            )
            super().__setitem__(key, 0)

    def __setitem__(self, key, value):
        super().__setitem__(key, value)
        mirror = self._mirror.get(key)
        if mirror is None:
            # an unspecced key appearing at runtime still gets a metric —
            # lint will flag it if the name is malformed
            mirror = self._mirror[key] = self._registry.counter(
                f"bqueryd_tpu_{key}_total", f"controller counter {key}"
            )
        mirror.set_total(value)
