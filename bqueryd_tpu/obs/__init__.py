"""Observability: metrics registry, distributed tracing, structured logging.

The serving-layer seeing-eye the reference never had (its whole surface was
``rpc.last_call_duration``, reference bqueryd/rpc.py:128-129).  Three pillars,
each its own module:

* :mod:`.metrics` — typed Counter/Gauge/Histogram on a per-node registry,
  Prometheus text rendering (``rpc.metrics()`` + the opt-in ``/metrics``
  endpoint in :mod:`.http`), log-scale latency buckets whose cross-worker
  merge is a vector add;
* :mod:`.trace`   — TraceContext propagation client→controller→worker→merge,
  span recording per phase, and the controller's timeline ring buffer behind
  ``rpc.trace(trace_id)``;
* :mod:`.logs`    — JSON log formatter carrying trace/query/node correlation
  ids, and the slow-query ring buffer behind ``rpc.slow_queries()``.

PR 3 adds the forensic/feedback tier:

* :mod:`.profile`   — XLA compile-time histograms, jit/persistent-cache
  hit/miss accounting, a per-shape program registry with cost_analysis
  FLOPs/bytes, and HBM-watermark gauges (``device.memory_stats``);
* :mod:`.flightrec` — a bounded always-on per-node flight ring plus the
  ``rpc.debug_bundle()`` cross-node artifact assembly (SIGUSR1 dumps it
  locally);
* :mod:`.health`    — per-worker rolling latency/error baselines scored
  ok/degraded/wedged behind ``rpc.health()``, fed back into dispatch
  affinity (degraded workers are deprioritized, never excluded).

PR 4 widens the worker surface with the shard-pipeline and working-set
cache families: ``bqueryd_tpu_pipeline_busy_seconds{stage=...}`` (per-stage
busy clocks from :mod:`bqueryd_tpu.parallel.pipeline` — busy sum > wall
proves stage overlap), ``bqueryd_tpu_workingset_*{segment=...}`` +
``bqueryd_tpu_result_cache_*`` (LRU cache hit/miss/eviction counters from
:mod:`bqueryd_tpu.ops.workingset`), and the HBM-pressure shed counter
``bqueryd_tpu_workingset_pressure_evictions``.

PR 10 adds the accounting tier on top of the spans:

* :mod:`.slo` — per-query critical-path attribution (``rpc.autopsy``:
  every query's wall decomposed into non-overlapping named segments with a
  >= 95% coverage contract), per-client-class SLO accounting
  (``bqueryd_tpu_slo_*`` margin histograms + burn-rate gauges), and the
  bounded controller snapshot ring behind ``rpc.timeline()``.

PR 12 adds the fleet tier:

* :mod:`.capacity` — the controller-resident queueing-model capacity
  accounting behind ``rpc.capacity()``: per-worker service rate μ from WRM
  histogram deltas (restart-reset guarded), per-class arrival rate λ from
  the admission tap, ρ = λ/μ with an M/G/1 predicted queue delay
  cross-checked against measured waits, ok/warm/saturated/overloaded
  states with hysteresis, a per-shard dispatch heat map, headroom-QPS /
  saturation-knee estimation, and a shadow scale_up/scale_down/rebalance
  advisor (logged, counted, never acted on).

The hot path (span recording + histogram observes + flight envelope events
+ compile-call accounting) can be disabled with ``BQUERYD_TPU_METRICS=0``
(or :func:`set_enabled`) — bench.py measures the enabled-vs-disabled delta
and holds it under 2% of the adaptive wall.  The controller's logic
counters (pruning, admission) are NOT gated: they steer behaviour, not just
visibility.  Forensic flight events (wedges, timeouts, worker removals,
errors) are never gated either — rare by construction, and the reason the
recorder exists.

Control-plane package: stdlib only, safe to import in every process.
"""

import os

from bqueryd_tpu.obs.logs import (  # noqa: F401
    JsonLogFormatter,
    SlowQueryLog,
    bind_log_context,
    log_context,
    slow_query_threshold_ms,
)
from bqueryd_tpu.obs.metrics import (  # noqa: F401
    LATENCY_BUCKETS_S,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    RegistryCounters,
    merge_histogram_snapshots,
)
from bqueryd_tpu.obs.trace import (  # noqa: F401
    PHASE_SPAN_NAMES,
    TRACE_KEY,
    SpanRecorder,
    TraceContext,
    TraceStore,
    current_trace,
    make_span,
    new_id,
    use_trace,
)
from bqueryd_tpu.obs.flightrec import (  # noqa: F401
    BUNDLE_SCHEMA,
    FlightRecorder,
    build_bundle,
    dump_bundle,
    redact_paths,
)
from bqueryd_tpu.obs.health import (  # noqa: F401
    STATUS_DEGRADED,
    STATUS_OK,
    STATUS_WEDGED,
    HealthScorer,
)
from bqueryd_tpu.obs import capacity  # noqa: F401
from bqueryd_tpu.obs import profile  # noqa: F401
from bqueryd_tpu.obs import slo  # noqa: F401

_enabled = True


def enabled():
    """Whether the observability hot path (spans + histogram observes) is on.
    ``BQUERYD_TPU_METRICS=0`` (read per call: live-tunable) or
    :func:`set_enabled(False)` turns it off; logic counters stay live."""
    return _enabled and os.environ.get("BQUERYD_TPU_METRICS", "1") != "0"


def set_enabled(value):
    """Process-wide switch (bench's overhead measurement seam)."""
    global _enabled
    _enabled = bool(value)
