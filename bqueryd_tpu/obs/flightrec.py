"""Flight recorder: the forensic artifact for wedges and hard timeouts.

When a node wedges, a dispatch blows ``DISPATCH_HARD_TIMEOUT``, or a worker
dies mid-query, PR 2's instruments say *that* it happened (a gauge flips, a
timeline is missing its tail) but not *what led up to it*.  The flight
recorder is the black box: a bounded, always-on ring per node of recent
envelopes, state transitions, and query outcomes.  Bounds are BOTH entry
count and bytes (a single huge traceback must not silently hold hours of
history hostage — nor grow without limit), with an eviction counter so
operators can size the ring from data.

``build_bundle`` assembles the cross-node JSON debug artifact behind the
controller's ``rpc.debug_bundle(trace_id=None)`` verb (and the SIGUSR1
local dump): controller flight ring + the trace timeline + metrics snapshot
+ slow queries + per-worker flight/compile/device-health snapshots absorbed
from WRM heartbeats.  A dead peer degrades the bundle, never fails it: its
last absorbed snapshot ships marked stale, and workers that never reported
are listed under ``"partial"``.  Every string in the bundle passes
:func:`redact_paths` — filesystem paths outside the declared data roots are
reduced to ``<redacted>/basename`` so a bundle can be attached to a public
bug report without leaking home directories or infra layout.

Control-plane module: stdlib only.
"""

import collections
import json
import os
import re
import tempfile
import threading
import time

#: schema /2 (PR 10): additive controller-section keys — ``autopsy`` (the
#: bundled trace's attributed critical path), ``calibration`` (measured-cost
#: store summary, PR 6), ``chaos`` (fault-injection stats, PR 8),
#: ``replication`` (replica placement, PR 8), ``batch_window`` (micro-batch
#: staging state, PR 9), ``slo`` (per-class accounting), ``timeline_ring``
#: (periodic registry snapshots).
#: schema /3 (PR 12): additive ``capacity`` controller-section key — the
#: fleet capacity model's freshly-evaluated snapshot (per-worker μ/ρ/state,
#: shard heat map, predicted-vs-measured queue delay, last shadow
#: recommendations; see obs.capacity).  /1 and /2 consumers keep working:
#: nothing was removed or renamed.
#: schema /4 (PR 16): additive ``serving`` controller-section key — the
#: semantic serving layer's snapshot (materialized-rollup entry states,
#: tracked-view heat, append epochs, and the most recent subsumption
#: decisions with chosen source + rejected candidates and reasons; see
#: bqueryd_tpu.serve).  Earlier consumers keep working unchanged.
BUNDLE_SCHEMA = "bqueryd_tpu.debug_bundle/4"

DEFAULT_CAPACITY = 512
DEFAULT_MAX_BYTES = 1 << 20  # 1 MiB of ring per node

#: WRM-absorbed worker snapshots older than this are marked ``stale`` in the
#: bundle (the worker may be dead; its last words still ship)
DEFAULT_STALE_AFTER_S = 120.0


def approx_json_bytes(obj):
    """Cheap recursive size estimate of ``obj``'s JSON form — used for ring
    byte accounting, where an exact ``json.dumps`` per hot-path event would
    cost more than the event itself."""
    if obj is None or isinstance(obj, bool):
        return 4
    if isinstance(obj, (int, float)):
        return 12
    if isinstance(obj, str):
        return len(obj) + 2
    if isinstance(obj, bytes):
        return len(obj) + 2
    if isinstance(obj, dict):
        return 2 + sum(
            approx_json_bytes(k) + approx_json_bytes(v) + 2
            for k, v in obj.items()
        )
    if isinstance(obj, (list, tuple, set)):
        return 2 + sum(approx_json_bytes(v) + 1 for v in obj)
    return len(str(obj)) + 2


class FlightRecorder:
    """Bounded ring of JSON-safe events, newest last.

    Hot-path callers gate themselves on ``obs.enabled()``; rare forensic
    events (wedge latches, timeouts, worker removals, errors) are recorded
    unconditionally — they are the reason this exists."""

    #: lock discipline, statically checked by bqueryd_tpu.analysis
    #: (lock-unguarded-attr)
    _bqtpu_guarded_ = {
        "_lock": ("_events", "_sizes", "_nbytes", "_evictions", "_seq"),
    }

    def __init__(self, node_id=None, capacity=None, max_bytes=None):
        if capacity is None:
            try:
                capacity = int(
                    os.environ.get("BQUERYD_TPU_FLIGHT_CAPACITY",
                                   DEFAULT_CAPACITY)
                )
            except ValueError:
                capacity = DEFAULT_CAPACITY
        if max_bytes is None:
            try:
                max_bytes = int(
                    os.environ.get("BQUERYD_TPU_FLIGHT_BYTES",
                                   DEFAULT_MAX_BYTES)
                )
            except ValueError:
                max_bytes = DEFAULT_MAX_BYTES
        self.node_id = node_id
        self.capacity = max(1, capacity)
        self.max_bytes = max(1024, max_bytes)
        self._events = collections.deque()
        self._sizes = collections.deque()
        self._nbytes = 0
        self._evictions = 0
        self._seq = 0
        self._lock = threading.Lock()

    def record(self, kind, **fields):
        event = {"ts": round(time.time(), 6), "kind": kind}
        event.update(fields)
        size = approx_json_bytes(event)
        with self._lock:
            self._seq += 1
            event["seq"] = self._seq
            self._events.append(event)
            self._sizes.append(size)
            self._nbytes += size
            while len(self._events) > self.capacity or (
                self._nbytes > self.max_bytes and len(self._events) > 1
            ):
                self._events.popleft()
                self._nbytes -= self._sizes.popleft()
                self._evictions += 1
        return event

    def events(self):
        """Full ring contents, oldest first."""
        with self._lock:
            return [dict(e) for e in self._events]

    def tail(self, limit=32):
        """The newest ``limit`` events, oldest first — the WRM-sized view."""
        with self._lock:
            picked = list(self._events)[-max(1, limit):]
            return [dict(e) for e in picked]

    @property
    def evictions(self):
        with self._lock:
            return self._evictions

    @property
    def nbytes(self):
        with self._lock:
            return self._nbytes

    def __len__(self):
        with self._lock:
            return len(self._events)


# -- redaction ----------------------------------------------------------------

#: an absolute filesystem path of depth >= 2; the lookbehind keeps URL
#: authority slashes (``tcp://host``) and interior path slashes from
#: matching as fresh path starts
_PATH_RE = re.compile(r"(?<![\w:/.])/(?:[\w.+-]+/)+[\w.+-]+")


def _redact_string(text, allowed):
    def sub(match):
        path = match.group(0)
        for prefix in allowed:
            if prefix and (
                path == prefix or path.startswith(prefix.rstrip("/") + "/")
            ):
                return path
        return "<redacted>/" + path.rsplit("/", 1)[-1]

    return _PATH_RE.sub(sub, text)


def redact_paths(obj, allowed_prefixes=()):
    """Recursively replace absolute filesystem paths outside the allowed
    roots with ``<redacted>/basename``.  Dict KEYS are redacted too (worker
    snapshots key some maps by filename).  Non-string leaves pass through
    untouched."""
    allowed = tuple(p for p in allowed_prefixes if p)
    if isinstance(obj, str):
        return _redact_string(obj, allowed)
    if isinstance(obj, dict):
        return {
            redact_paths(k, allowed): redact_paths(v, allowed)
            for k, v in obj.items()
        }
    if isinstance(obj, (list, tuple)):
        return [redact_paths(v, allowed) for v in obj]
    return obj


# -- bundle assembly ----------------------------------------------------------

def build_bundle(controller_section, worker_snapshots, trace_id=None,
                 allowed_path_prefixes=(), stale_after_s=DEFAULT_STALE_AFTER_S,
                 now=None):
    """Assemble the cross-node debug artifact (deterministic schema).

    ``controller_section``: the controller's own state dict (flight ring,
    counters, metrics, trace timeline, slow queries, health, ...).
    ``worker_snapshots``: ``{worker_id: {"data": <absorbed WRM debug snapshot
    or None>, "ts": <absorb time>, "registered": bool}}``.  Workers with no
    absorbed data land in ``"partial"`` — a dead or never-reporting peer
    degrades the bundle instead of failing it.
    """
    now = time.time() if now is None else now
    workers = {}
    partial = []
    for worker_id in sorted(worker_snapshots):
        snap = worker_snapshots[worker_id] or {}
        data = snap.get("data")
        entry = {
            "registered": bool(snap.get("registered")),
            "snapshot": data,
        }
        ts = snap.get("ts")
        if ts is not None:
            entry["age_s"] = round(max(now - ts, 0.0), 3)
            entry["stale"] = entry["age_s"] > stale_after_s
        if data is None:
            partial.append(worker_id)
        workers[worker_id] = entry
    bundle = {
        "schema": BUNDLE_SCHEMA,
        "generated_ts": round(now, 6),
        "trace_id": trace_id,
        "controller": controller_section,
        "workers": workers,
        "partial": partial,
    }
    return redact_paths(bundle, allowed_path_prefixes)


def dump_bundle(bundle, role="node", directory=None):
    """Write a bundle as one JSON file (the SIGUSR1 local dump); returns the
    path.  Directory: ``BQUERYD_TPU_DEBUG_DIR``, default the system tmpdir."""
    directory = (
        directory
        or os.environ.get("BQUERYD_TPU_DEBUG_DIR")
        or tempfile.gettempdir()
    )
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(
        directory,
        f"bqueryd_tpu_debug_{role}_{os.getpid()}_{int(time.time())}.json",
    )
    with open(path, "w") as f:
        json.dump(bundle, f, default=str, indent=1)
    return path
