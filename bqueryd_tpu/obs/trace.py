"""Distributed tracing: TraceContext propagation + span recording + storage.

A query's identity is a ``trace_id`` minted by the RPC client and injected
into the message envelope (``messages.Message`` ``"trace"`` key, see the
schema note in :mod:`bqueryd_tpu.messages`).  Every hop derives child spans:

    client rpc span                                (root; client-side)
      └─ controller "groupby" span                 (query lifetime)
           ├─ "admission" span                     (queue wait)
           ├─ "plan" span                          (compile + rewrite)
           └─ "dispatch" span (per work unit)      (queue→send)
                └─ worker "calc" span              (whole CalcMessage)
                     ├─ "storage_decode" ("open")
                     ├─ "align" / "filter" ("mask")
                     ├─ "h2d_transfer" ("layout")
                     ├─ "kernel" ("aggregate" — the psum collective merge is
                     │            fused into this compiled program)
                     ├─ "d2h_fetch" ("fetch" — device→host fetch of the
                     │            merged result buffer)
                     ├─ "merge" ("collect"/"hostmerge" — materialization of
                     │           the collectively-merged partials)
                     └─ "reply_serialization" ("serialize")

The full span-name taxonomy is DECLARED in ``messages.SPAN_SCHEMA`` and
cross-checked by the span-coverage lint (``bqueryd_tpu.analysis.spans``)
against every literal span site and against the attribution map in
:mod:`bqueryd_tpu.obs.slo` — a new span name ships declared and
attributable, or the lint fails.

Workers return their spans in calc replies (``"spans"`` key); the controller
assembles the per-query timeline and keeps it in a :class:`TraceStore` ring
buffer, retrievable via ``rpc.trace(trace_id)`` — an actual waterfall instead
of eyeballing ``last_call_duration``.

Span timestamps are wall-clock (``time.time()``) so spans from different
nodes interleave on one timeline; durations are measured with
``time.perf_counter`` so an NTP step can't make a span negative.

The active context also rides a contextvar so ``utils.tracing.trace_span``
can tag ``jax.profiler`` annotations with the trace id — device profiler
timelines line up with RPC spans.

Control-plane module: stdlib only.
"""

import contextlib
import contextvars
import os
import time

#: envelope key carrying the wire TraceContext (see messages.py schema note)
TRACE_KEY = "trace"

#: worker PhaseTimer phase -> public span name (the taxonomy in the module
#: docstring); unmapped phases keep their own name
PHASE_SPAN_NAMES = {
    "open": "storage_decode",
    "mask": "filter",
    "join": "join_probe",
    "rollup": "window_rollup",
    "layout": "h2d_transfer",
    "aggregate": "kernel",
    "fetch": "d2h_fetch",
    "collect": "merge",
    "hostmerge": "merge",
    "serialize": "reply_serialization",
}

_current = contextvars.ContextVar("bqueryd_tpu_trace", default=None)


def new_id(nbytes=8):
    return os.urandom(nbytes).hex()


class TraceContext:
    """(trace_id, span_id, parent_span_id) — the propagation triple.

    ``span_id`` is the ACTIVE span at the sender; a receiver parents its own
    root span to it.  Wire form is a plain JSON-safe dict so it rides the
    message envelope without pickling."""

    __slots__ = ("trace_id", "span_id", "parent_span_id")

    def __init__(self, trace_id, span_id, parent_span_id=None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_span_id = parent_span_id

    @classmethod
    def new_root(cls):
        return cls(trace_id=new_id(16), span_id=new_id())

    def child(self):
        """A context for the next hop: fresh span under the current one."""
        return TraceContext(
            trace_id=self.trace_id,
            span_id=new_id(),
            parent_span_id=self.span_id,
        )

    def to_wire(self):
        wire = {"trace_id": self.trace_id, "span_id": self.span_id}
        if self.parent_span_id:
            wire["parent_span_id"] = self.parent_span_id
        return wire

    @classmethod
    def from_wire(cls, wire):
        """Parse the envelope dict; None (or malformed) -> None."""
        if not isinstance(wire, dict):
            return None
        trace_id = wire.get("trace_id")
        span_id = wire.get("span_id")
        if not isinstance(trace_id, str) or not isinstance(span_id, str):
            return None
        return cls(trace_id, span_id, wire.get("parent_span_id"))


def current_trace():
    """The TraceContext bound to this thread/task, or None."""
    return _current.get()


@contextlib.contextmanager
def use_trace(ctx):
    """Bind ``ctx`` as the active TraceContext for the block (contextvar:
    thread- and task-local)."""
    token = _current.set(ctx)
    try:
        yield ctx
    finally:
        _current.reset(token)


def make_span(trace_id, name, start_ts, duration_s, span_id=None,
              parent_span_id=None, node=None, tags=None):
    """One JSON-safe span record."""
    span = {
        "trace_id": trace_id,
        "span_id": span_id or new_id(),
        "parent_span_id": parent_span_id,
        "name": name,
        "start_ts": round(float(start_ts), 6),
        "duration_s": round(float(duration_s), 6),
    }
    if node is not None:
        span["node"] = node
    if tags:
        span["tags"] = dict(tags)
    return span


class SpanRecorder:
    """Collects spans for one unit of work (a worker's CalcMessage, say).

    Opens a root span at construction; child spans default their parent to
    it.  ``export`` closes the root (duration = lifetime so far) and returns
    the JSON-safe span list, ready for a reply's ``"spans"`` field."""

    def __init__(self, trace_id, node=None, root_name="calc",
                 root_parent=None, span_names=None):
        self.trace_id = trace_id
        self.node = node
        self.span_names = span_names or {}
        self.root_span_id = new_id()
        self._root_name = root_name
        self._root_parent = root_parent
        self._root_start = time.time()
        self._root_clock = time.perf_counter()
        self.spans = []

    def record(self, name, start_ts, duration_s, parent_span_id=None,
               tags=None):
        self.spans.append(
            make_span(
                self.trace_id,
                self.span_names.get(name, name),
                start_ts,
                duration_s,
                parent_span_id=parent_span_id or self.root_span_id,
                node=self.node,
                tags=tags,
            )
        )

    @contextlib.contextmanager
    def span(self, name, parent_span_id=None, tags=None):
        start_ts = time.time()
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.record(
                name, start_ts, time.perf_counter() - t0,
                parent_span_id=parent_span_id, tags=tags,
            )

    def export(self, tags=None):
        """Root span + children, oldest first.  ``tags`` land on the ROOT
        span — per-unit facts that belong to the whole calc (e.g. the
        worker's device-memory attribution for this query)."""
        root = make_span(
            self.trace_id,
            self._root_name,
            self._root_start,
            time.perf_counter() - self._root_clock,
            span_id=self.root_span_id,
            parent_span_id=self._root_parent,
            node=self.node,
            tags=tags,
        )
        return [root] + sorted(self.spans, key=lambda s: s["start_ts"])


class TraceStore:
    """Ring buffer of assembled per-query timelines, keyed by trace_id.

    Bounded by BOTH entry count (``BQUERYD_TPU_TRACE_BUFFER``, default 256)
    and bytes (``BQUERYD_TPU_TRACE_BUFFER_BYTES``, default 16 MiB): span
    counts scale with shard counts, so an entry-only cap let a long-running
    controller's wide-query timelines grow without limit.  ``evictions``
    counts entries dropped for either reason (exported as a gauge).  A
    timeline is ``{"trace_id", "wall_s", "created_ts", "ok", "spans": [...]}``
    plus any extra keys the controller attaches (filenames, pruned, ...)."""

    DEFAULT_MAX_BYTES = 16 << 20

    def __init__(self, capacity=None, max_bytes=None):
        if capacity is None:
            try:
                capacity = int(os.environ.get("BQUERYD_TPU_TRACE_BUFFER", 256))
            except ValueError:
                capacity = 256
        if max_bytes is None:
            try:
                max_bytes = int(
                    os.environ.get(
                        "BQUERYD_TPU_TRACE_BUFFER_BYTES",
                        self.DEFAULT_MAX_BYTES,
                    )
                )
            except ValueError:
                max_bytes = self.DEFAULT_MAX_BYTES
        self.capacity = max(1, capacity)
        self.max_bytes = max(1024, max_bytes)
        self._order = []    # trace_ids, oldest first
        self._store = {}
        self._sizes = {}
        self._nbytes = 0
        self.evictions = 0

    def put(self, trace_id, timeline):
        from bqueryd_tpu.obs.flightrec import approx_json_bytes

        if trace_id in self._store:
            self._order.remove(trace_id)
            self._nbytes -= self._sizes.pop(trace_id, 0)
        size = approx_json_bytes(timeline)
        self._store[trace_id] = timeline
        self._sizes[trace_id] = size
        self._nbytes += size
        self._order.append(trace_id)
        while len(self._order) > self.capacity or (
            self._nbytes > self.max_bytes and len(self._order) > 1
        ):
            evicted = self._order.pop(0)
            self._store.pop(evicted, None)
            self._nbytes -= self._sizes.pop(evicted, 0)
            self.evictions += 1

    def get(self, trace_id):
        return self._store.get(trace_id)

    def latest(self):
        """The newest timeline (or None) — the debug bundle's default when
        no trace_id is requested."""
        return self._store.get(self._order[-1]) if self._order else None

    @property
    def nbytes(self):
        return self._nbytes

    def __len__(self):
        return len(self._store)
