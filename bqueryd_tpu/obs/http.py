"""Opt-in stdlib ``/metrics`` endpoint for Prometheus scrapes.

``maybe_start(registry)`` reads ``BQUERYD_TPU_METRICS_PORT``: unset or empty
means no server (the default — RPC ``rpc.metrics()`` always works without
it); an integer binds a ThreadingHTTPServer on that port (0 = ephemeral,
handy for tests) serving:

* ``GET /metrics``  — the registry's Prometheus text exposition;
* ``GET /healthz``  — ``ok`` (a liveness probe that costs nothing).

One port serves ONE node's registry: in the production topology each role is
its own process, so controller and workers each get their own port (set the
env per process; in-process test clusters pass ``port=0`` explicitly).

Control-plane module: stdlib only.
"""

import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class MetricsServer:
    """A running /metrics endpoint; ``close()`` releases the port."""

    def __init__(self, registry, port, host="0.0.0.0"):
        server = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                if self.path.split("?")[0] == "/metrics":
                    body = server.registry.render().encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif self.path.split("?")[0] == "/healthz":
                    body, ctype = b"ok\n", "text/plain; charset=utf-8"
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):
                pass  # scrape noise never reaches the node's logger

        self.registry = registry
        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name=f"metrics-http-{self.port}",
            daemon=True,
        )
        self._thread.start()

    def close(self):
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:
            pass


def maybe_start(registry, logger=None, port=None):
    """Start a MetricsServer when configured; None otherwise.

    ``port=None`` reads BQUERYD_TPU_METRICS_PORT (unset/empty -> off).  A
    bind failure (port taken — e.g. two nodes in one test process sharing
    the env) is logged and swallowed: metrics export must never stop a node
    from serving queries."""
    if port is None:
        raw = os.environ.get("BQUERYD_TPU_METRICS_PORT", "").strip()
        if not raw:
            return None
        try:
            port = int(raw)
        except ValueError:
            if logger is not None:
                logger.warning(
                    "unparseable BQUERYD_TPU_METRICS_PORT=%r; /metrics off", raw
                )
            return None
    try:
        server = MetricsServer(registry, port)
    except OSError as exc:
        if logger is not None:
            logger.warning("could not bind /metrics on port %s: %s", port, exc)
        return None
    if logger is not None:
        logger.info("serving /metrics on port %d", server.port)
    return server
